"""Preemption-planner parity vs the oracle DefaultPreemption plugin.

The fast planner (scheduler/preemption.py) replaces the per-node
selectVictimsOnNode dry-run with one vectorized pass whenever the
preemptor's filter envelope reduces to static node gates + resource fit.
Inside that envelope its decisions must be EXACTLY the oracle's —
default_preemption.go:320 dryRunPreemption semantics — which this suite
pins with randomized clusters (the same strategy test_kernel_parity.py
uses for the scheduling kernel).

The DEVICE planner (scheduler/preemption_device.py + ops/whatif.py) is
the rung above: victim search as one fused what-if launch per preemptor.
Its parity surface is pinned three ways here: device vs fast vs oracle
on the fast envelope (randomized, PDBs, nominated load, start times),
and device vs oracle on the affinity / topology-spread extension the
numpy envelope must reject.
"""

from __future__ import annotations

import random

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.scheduler.framework.interface import CycleState
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
from kubernetes_tpu.scheduler.internal.nominator import PodNominator
from kubernetes_tpu.scheduler.preemption import (
    FastPreemptionPlanner,
    WaveAntiTerms,
    fast_eligible,
)
from kubernetes_tpu.scheduler.preemption_device import (
    DevicePreemptionPlanner,
    device_eligible,
)
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
from kubernetes_tpu.testing.synth import make_node, make_pod

from .test_preemption import _post_filter


def _mk_backend(nodes, pods) -> TPUBackend:
    """A CPU TPUBackend with the cluster mirrored into its encoding via
    the CacheListener hooks — the device planner's what-if context then
    builds from a scratch snapshot of that encoding (no session needed:
    the same path the pallas/sharded sessions take)."""
    b = TPUBackend()
    b.whatif = True  # CPU default is off (platform-gated); tests opt in
    for n in nodes:
        b.on_add_node(n)
    for p in pods:
        b.on_add_pod(p, p.spec.node_name)
    return b


def _device_plan(snapshot, wave, backend, nominator=None, pdbs=None,
                 fast_ok=False):
    planner = DevicePreemptionPlanner(
        snapshot, nominator, backend, pdbs=pdbs,
        eligibility={v1.pod_key(p): (True, fast_ok) for p in wave},
    )
    cands = planner.plan(wave)
    return planner, cands


def _random_cluster(rng: random.Random, n_nodes: int):
    nodes = []
    pods = []
    for i in range(n_nodes):
        taints = None
        if rng.random() < 0.1:
            taints = [v1.Taint(key="dedicated", value="x", effect="NoSchedule")]
        nodes.append(
            make_node(
                f"n{i}",
                cpu=str(rng.choice([2, 4, 8])),
                memory="16Gi",
                pods=rng.choice([3, 5, 110]),
                unschedulable=rng.random() < 0.05,
                taints=taints,
            )
        )
        # mostly-saturated nodes: preemption paths only exercise when
        # the pending pod cannot fit anywhere as-is
        for j in range(rng.randint(2, 4)):
            pods.append(
                make_pod(
                    f"p{i}-{j}",
                    cpu=f"{rng.choice([900, 1500, 2000, 2500])}m",
                    memory=rng.choice(["64Mi", "512Mi", "2Gi"]),
                    node_name=f"n{i}",
                    priority=rng.choice([0, 1, 5, 50, 200]),
                )
            )
    return nodes, pods


def _plan_single(snapshot, pod, nominator=None):
    planner = FastPreemptionPlanner(snapshot, nominator)
    (cand,) = planner.plan([pod])
    return cand, planner.fits_now[0]


class TestParityFuzz:
    def test_matches_oracle_on_random_clusters(self):
        rng = random.Random(4)
        agree_preempt = 0
        agree_none = 0
        for trial in range(40):
            nodes, pods = _random_cluster(rng, rng.randint(3, 12))
            snapshot = Snapshot.from_objects(pods, nodes)
            pending = make_pod(
                "high",
                # 9000m exceeds every node shape: exercises the
                # no-candidate agreement too
                cpu=f"{rng.choice([1000, 2500, 3500, 9000])}m",
                memory="1Gi",
                priority=100,
            )
            assert fast_eligible(pending, snapshot, [], [])
            cand, fits_now = _plan_single(snapshot, pending)
            if fits_now:
                # the oracle never sees such pods (the scheduler only
                # preempts after a failed cycle); skip
                continue
            result, status = _post_filter(snapshot, pending)
            if cand is None:
                assert result is None, (
                    f"trial {trial}: planner found nothing, oracle chose "
                    f"{result.nominated_node_name} "
                    f"{[p.metadata.name for p in result.victims]}"
                )
                agree_none += 1
            else:
                assert result is not None, (
                    f"trial {trial}: planner chose {cand.node_name}, "
                    "oracle found nothing"
                )
                assert cand.node_name == result.nominated_node_name, trial
                assert sorted(p.metadata.name for p in cand.victims) == sorted(
                    p.metadata.name for p in result.victims
                ), trial
                agree_preempt += 1
        # the fuzz must actually exercise both outcomes
        assert agree_preempt >= 5
        assert agree_none >= 1

    def test_matches_oracle_with_nominated_load(self):
        """A node already nominated by an equal-priority pod has less
        usable capacity (framework.go:610 double-filtering)."""
        rng = random.Random(11)
        checked = 0
        for trial in range(20):
            nodes, pods = _random_cluster(rng, rng.randint(2, 6))
            snapshot = Snapshot.from_objects(pods, nodes)
            nominator = PodNominator()
            ghost = make_pod("ghost", cpu="2", memory="1Gi", priority=100)
            nominator.add_nominated_pod(
                ghost, nodes[rng.randrange(len(nodes))].metadata.name
            )
            pending = make_pod("high", cpu="2500m", memory="1Gi", priority=100)
            cand, fits_now = _plan_single(snapshot, pending, nominator)
            if fits_now:
                continue
            from .test_preemption import _framework

            f = _framework(snapshot)
            f.nominator = nominator
            state = CycleState()
            assert f.run_pre_filter_plugins(state, pending) is None
            statuses = {}
            for ni in snapshot.list():
                s = f.run_filter_plugins(state, pending, ni)
                if s:
                    statuses[ni.node.metadata.name] = next(iter(s.values()))
            plugin = f.plugins["DefaultPreemption"]
            result, status = plugin.post_filter(state, pending, statuses)
            if cand is None:
                assert result is None, trial
            else:
                assert result is not None, trial
                assert cand.node_name == result.nominated_node_name, trial
                assert sorted(p.metadata.name for p in cand.victims) == sorted(
                    p.metadata.name for p in result.victims
                ), trial
                checked += 1
        assert checked >= 3


class TestWaveSemantics:
    def test_wave_claims_distinct_victims_and_capacity(self):
        """A wave of identical preemptors on a saturated cluster: every
        pod gets a candidate, no victim is claimed twice, and no node is
        oversubscribed by the nominations."""
        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(20)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(20)
            for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(20)
        ]
        planner = FastPreemptionPlanner(snapshot, PodNominator())
        cands = planner.plan(wave)
        assert all(c is not None for c in cands)
        victim_keys = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(victim_keys) == len(set(victim_keys)), "victim claimed twice"
        # nominations must never oversubscribe a node: each node holds
        # 4 victims x 0.9 cpu on 4 cpu, so at most 4 preemptors (0.9
        # each) fit even with every victim evicted
        per_node = {}
        for c in cands:
            per_node[c.node_name] = per_node.get(c.node_name, 0) + 1
            assert len(c.victims) == 1
        for node, count in per_node.items():
            assert count <= 4

    def test_wave_saturates_then_fails(self):
        """Once every lower-priority pod on a node is spoken for, later
        wave pods must not plan preemption there."""
        nodes = [make_node("n0", cpu="4", pods=10)]
        pods = [
            make_pod(f"low{j}", cpu="1900m", memory="64Mi",
                     node_name="n0", priority=1)
            for j in range(2)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="1900m", memory="64Mi", priority=100)
            for k in range(4)
        ]
        planner = FastPreemptionPlanner(snapshot, PodNominator())
        cands = planner.plan(wave)
        # 2 victims, each freeing room for one preemptor; the first two
        # plans claim them, the rest find nothing
        assert sum(1 for c in cands if c is not None) == 2
        assert sum(1 for c in cands if c is None) == 2

    def test_fits_now_detected(self):
        nodes = [make_node("n0", cpu="4"), make_node("n1", cpu="4")]
        pods = [make_pod("low", cpu="3500m", node_name="n0", priority=1)]
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("hi", cpu="1", priority=100)
        cand, fits_now = _plan_single(snapshot, pending)
        assert fits_now and cand is None


class TestQueueActivate:
    def test_activate_skips_backoff(self):
        from kubernetes_tpu.scheduler.internal.queue import PriorityQueue

        q = PriorityQueue(pod_initial_backoff=100.0, pod_max_backoff=100.0)
        pod = make_pod("p", cpu="1")
        q.add(pod)
        info = q.pop(timeout=0)
        assert info is not None
        q.add_unschedulable_if_not_present(info, q.scheduling_cycle)
        # parked in unschedulableQ: a plain pop times out
        assert q.pop(timeout=0) is None
        assert q.activate(pod)
        got = q.pop(timeout=0)
        assert got is not None and got.pod.metadata.name == "p"
        # not parked anywhere now
        assert not q.activate(pod)

    def test_activate_from_backoff_queue(self):
        from kubernetes_tpu.scheduler.internal.queue import PriorityQueue

        q = PriorityQueue(pod_initial_backoff=100.0, pod_max_backoff=100.0)
        pod = make_pod("p", cpu="1")
        q.add(pod)
        info = q.pop(timeout=0)
        q.move_all_to_active_or_backoff_queue("NodeAdd")  # bump move cycle
        q.add_unschedulable_if_not_present(info, 0)  # -> backoffQ (raced)
        assert q.pop(timeout=0) is None  # 100s backoff
        assert q.activate(pod)
        assert q.pop(timeout=0) is not None


class TestInFlightTracking:
    def test_preemptor_activates_after_last_victim_echo(self):
        """End-to-end through the live loop on the CPU backend of the
        TPU scheduler: a preemptor waits parked until every victim's
        delete echoes, then binds on its nominated node without waiting
        out backoff."""
        import time

        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import Clientset, SharedInformerFactory

        api = APIServer()
        cs = Clientset(api)
        cs.nodes.create(make_node("n0", cpu="4", pods=10))
        for j in range(4):
            cs.pods.create(
                make_pod(f"low{j}", cpu="900m", memory="64Mi",
                         node_name="", priority=1)
            )
        factory = SharedInformerFactory(cs)
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        sched = Scheduler(cs, factory, backend="tpu",
                          pod_initial_backoff=30.0, pod_max_backoff=30.0)
        factory.start()
        assert factory.wait_for_cache_sync()
        sched.start()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                pods, _ = cs.pods.list(namespace="default")
                if sum(1 for p in pods if p.spec.node_name) == 4:
                    break
                time.sleep(0.05)
            hi = make_pod("hi", cpu="900m", memory="64Mi", priority=100)
            cs.pods.create(hi)
            # 30s backoff configured: binding within a few seconds proves
            # the activate path, not the backoff clock, re-admitted it
            deadline = time.monotonic() + 20
            bound = False
            while time.monotonic() < deadline:
                got = cs.pods.get("hi", "default")
                if got.spec.node_name:
                    bound = True
                    break
                time.sleep(0.05)
            assert bound, "preemptor did not bind"
            assert got.spec.node_name == "n0"
            pods, _ = cs.pods.list(namespace="default")
            assert sum(1 for p in pods if p.metadata.name.startswith("low")
                       and p.spec.node_name) == 3
            # tracking state drained
            assert not sched._node_waves
            assert not sched._inflight_preemptors
        finally:
            sched.stop()
            factory.stop()


class TestEligibility:
    def test_gates(self):
        nodes = [make_node("n0")]
        snapshot = Snapshot.from_objects([], nodes)
        pod = make_pod("p", cpu="1", priority=10)
        assert fast_eligible(pod, snapshot, [], [])
        # PDBs are inside the envelope now (vectorized PDB partitioning)
        assert fast_eligible(pod, snapshot, [object()], [])
        assert not fast_eligible(pod, snapshot, [], [object()])  # extenders
        never = make_pod("p2", cpu="1", priority=10)
        never.spec.preemption_policy = "Never"
        assert not fast_eligible(never, snapshot, [], [])
        spread = make_pod("p3", cpu="1", priority=10)
        spread.spec.topology_spread_constraints = [
            v1.TopologySpreadConstraint(
                max_skew=1, topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
            )
        ]
        assert not fast_eligible(spread, snapshot, [], [])
        # required anti-affinity gates per POD: only a preemptor the
        # term MATCHES falls back (one anti pod must no longer disable
        # the planner for the whole cluster — VERDICT r4 #6)
        anti = make_pod(
            "anti", cpu="1", node_name="n0",
            affinity=v1.Affinity(
                pod_anti_affinity=v1.PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=[
                        v1.PodAffinityTerm(
                            label_selector=v1.LabelSelector(
                                match_labels={"app": "x"}
                            ),
                            topology_key="kubernetes.io/hostname",
                        )
                    ]
                )
            ),
        )
        snapshot2 = Snapshot.from_objects([anti], nodes)
        assert fast_eligible(pod, snapshot2, [], [])  # no label match
        matched = make_pod("pm", cpu="1", priority=10,
                           labels={"app": "x"})
        assert not fast_eligible(matched, snapshot2, [], [])


class TestPDBParityFuzz:
    """PDB-covered victims ride the planner: filterPodsWithPDBViolation
    partitioning, violating-first reprieve, and the violations-first
    pick ladder must match the oracle exactly."""

    def _random_pdb_cluster(self, rng: random.Random, n_nodes: int):
        nodes, pods = [], []
        # sometimes every pod shares one app + an exhausted budget, so
        # violations are unavoidable and survive into the chosen
        # candidate (the violations ladder + violating-first reprieve
        # both get exercised)
        apps = ["a", "b", "c"] if rng.random() < 0.5 else ["a"]
        for i in range(n_nodes):
            nodes.append(make_node(
                f"n{i}", cpu=str(rng.choice([2, 4, 8])), memory="16Gi",
                pods=rng.choice([4, 6, 110]),
            ))
            for j in range(rng.randint(2, 6)):
                pod = make_pod(
                    f"p{i}-{j}",
                    cpu=f"{rng.choice([900, 1500, 2000, 2500])}m",
                    memory=rng.choice(["64Mi", "512Mi"]),
                    node_name=f"n{i}",
                    priority=rng.choice([0, 1, 5, 50]),
                    labels={"app": rng.choice(apps)},
                )
                # randomized start times: MoreImportantPod order (prio
                # desc, start asc) must genuinely differ from ni.pods
                # order, or the allowance-consumption-order contract
                # (:612 sort before filterPodsWithPDBViolation) is
                # untested
                pod.status.start_time = rng.random() * 100.0
                pods.append(pod)
        pdbs = []
        for k in range(rng.randint(1, 2)):
            pdbs.append(v1.PodDisruptionBudget(
                metadata=v1.ObjectMeta(name=f"pdb{k}", namespace="default"),
                spec=v1.PodDisruptionBudgetSpec(
                    selector=v1.LabelSelector(
                        match_labels={"app": rng.choice(apps)}),
                ),
                status=v1.PodDisruptionBudgetStatus(
                    # 1/2/3 with up to 6 matching victims per node: the
                    # PARTIALLY consumable range, where which victims
                    # land in the violating group depends entirely on
                    # consumption order
                    disruptions_allowed=rng.choice([0, 1, 2, 3]),
                ),
            ))
        return nodes, pods, pdbs

    def test_pdb_partial_budget_consumed_in_importance_order(self):
        """A budget covering MORE victims than it allows must be
        consumed in MoreImportantPod order (priority desc, earlier start
        first — the :612 sort runs before filterPodsWithPDBViolation),
        so the LEAST important victims land in the violating group.
        Consuming in ni.pods order instead flips which pods violate, and
        the violating-first eviction ORDER makes that observable."""
        nodes = [make_node("n0", cpu="4", memory="16Gi", pods=110)]
        specs = [  # (name, priority, start) in ni.pods order
            ("p0", 0, 5.0), ("p1", 10, 1.0), ("p2", 10, 3.0), ("p3", 5, 2.0),
        ]
        pods = []
        for name, prio, start in specs:
            p = make_pod(name, cpu="900m", node_name="n0", priority=prio,
                         labels={"app": "db"})
            p.status.start_time = start
            pods.append(p)
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="db-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "db"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=2),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        # needs every victim gone: no reprieve, all four evicted
        pending = make_pod("high", cpu="3900m", priority=100)
        planner = FastPreemptionPlanner(snapshot, None, pdbs=[pdb])
        (cand,) = planner.plan([pending])
        assert cand is not None and not planner.fits_now[0]
        # consumption order p1(10,1) p2(10,3) p3(5) p0(0): the budget's
        # two allowances go to p1+p2, so p3+p0 violate — and evict FIRST
        assert cand.num_pdb_violations == 2
        assert [p.metadata.name for p in cand.victims] == \
            ["p3", "p0", "p1", "p2"]
        result, status = _post_filter(snapshot, pending, pdbs=[pdb])
        assert result is not None
        assert [p.metadata.name for p in result.victims] == \
            [p.metadata.name for p in cand.victims]

    def test_matches_oracle_with_pdbs(self):
        rng = random.Random(21)
        agree_preempt = 0
        saw_violations = 0
        for trial in range(40):
            nodes, pods, pdbs = self._random_pdb_cluster(
                rng, rng.randint(3, 10))
            snapshot = Snapshot.from_objects(pods, nodes)
            pending = make_pod(
                "high",
                cpu=f"{rng.choice([1000, 2500, 3500, 9000])}m",
                memory="1Gi", priority=100,
            )
            assert fast_eligible(pending, snapshot, pdbs, [])
            planner = FastPreemptionPlanner(snapshot, None, pdbs=pdbs)
            (cand,) = planner.plan([pending])
            if planner.fits_now[0]:
                continue
            result, status = _post_filter(snapshot, pending, pdbs=pdbs)
            if cand is None:
                assert result is None, trial
            else:
                assert result is not None, trial
                assert cand.node_name == result.nominated_node_name, trial
                assert [p.metadata.name for p in cand.victims] == [
                    p.metadata.name for p in result.victims
                ], trial
                agree_preempt += 1
                if cand.num_pdb_violations:
                    saw_violations += 1
        assert agree_preempt >= 8
        assert saw_violations >= 1  # the fuzz must exercise violations

    def test_pdb_protected_node_avoided(self):
        """Two equivalent nodes; the victims on one are PDB-protected
        with no disruptions left — the planner must pick the other
        (fewest violations is the FIRST pick-one criterion)."""
        nodes = [make_node("n0", cpu="4"), make_node("n1", cpu="4")]
        pods = [
            make_pod("v0", cpu="3500m", node_name="n0", priority=1,
                     labels={"app": "db"}),
            make_pod("v1", cpu="3500m", node_name="n1", priority=1,
                     labels={"app": "web"}),
        ]
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="db-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "db"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=0),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("hi", cpu="2", priority=100)
        planner = FastPreemptionPlanner(snapshot, None, pdbs=[pdb])
        (cand,) = planner.plan([pending])
        assert cand is not None
        assert cand.node_name == "n1"
        assert cand.num_pdb_violations == 0

    def test_pdb_wave_throughput_envelope(self):
        """A whole wave with PDBs present plans through the planner (no
        oracle fallback) and claims distinct victims."""
        from kubernetes_tpu.scheduler.internal.nominator import PodNominator

        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(10)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1,
                     labels={"app": "w"})
            for i in range(10) for j in range(4)
        ]
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="w-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "w"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=100),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(10)
        ]
        planner = FastPreemptionPlanner(
            snapshot, PodNominator(), pdbs=[pdb])
        cands = planner.plan(wave)
        assert all(c is not None for c in cands)
        victim_keys = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(victim_keys) == len(set(victim_keys))
        assert all(c.num_pdb_violations == 0 for c in cands)


class TestDeviceParityFuzz:
    """Three-way parity: device what-if planner vs numpy fast planner vs
    the oracle DefaultPreemption plugin, on the fast envelope (where all
    three run). The device rung must be bit-identical on node choice,
    victim sets, victim ORDER, and PDB accounting."""

    def test_three_way_random_clusters(self):
        rng = random.Random(7)
        agree = none = 0
        for trial in range(25):
            nodes, pods = _random_cluster(rng, rng.randint(3, 10))
            snapshot = Snapshot.from_objects(pods, nodes)
            backend = _mk_backend(nodes, pods)
            pending = make_pod(
                "high",
                cpu=f"{rng.choice([1000, 2500, 3500, 9000])}m",
                memory="1Gi", priority=100,
            )
            dp, (dc,) = _device_plan(
                snapshot, [pending], backend, nominator=PodNominator())
            assert dp.planner_paths == ["device"], (trial, dp.planner_paths)
            fp = FastPreemptionPlanner(snapshot, PodNominator())
            (fc,) = fp.plan([pending])
            assert dp.fits_now == fp.fits_now, trial
            if dp.fits_now[0]:
                continue
            result, _ = _post_filter(snapshot, pending)
            if dc is None:
                assert fc is None and result is None, trial
                none += 1
            else:
                assert fc is not None and result is not None, trial
                assert dc.node_name == fc.node_name \
                    == result.nominated_node_name, trial
                assert [p.metadata.name for p in dc.victims] == [
                    p.metadata.name for p in fc.victims
                ], trial
                assert sorted(p.metadata.name for p in dc.victims) == sorted(
                    p.metadata.name for p in result.victims
                ), trial
                agree += 1
        assert agree >= 4 and none >= 1

    def test_three_way_with_pdbs(self):
        """Random partial budgets + random start times: the violating
        split, violating-first reprieve ORDER, and the violations-first
        pick ladder ride the device rung bit-identically."""
        helper = TestPDBParityFuzz()
        rng = random.Random(33)
        agree = saw_violations = 0
        for trial in range(15):
            nodes, pods, pdbs = helper._random_pdb_cluster(
                rng, rng.randint(3, 8))
            snapshot = Snapshot.from_objects(pods, nodes)
            backend = _mk_backend(nodes, pods)
            pending = make_pod(
                "high",
                cpu=f"{rng.choice([1000, 2500, 3500, 9000])}m",
                memory="1Gi", priority=100,
            )
            dp, (dc,) = _device_plan(snapshot, [pending], backend, pdbs=pdbs)
            assert dp.planner_paths == ["device"], trial
            fp = FastPreemptionPlanner(snapshot, None, pdbs=pdbs)
            (fc,) = fp.plan([pending])
            assert dp.fits_now == fp.fits_now, trial
            if dp.fits_now[0]:
                continue
            result, _ = _post_filter(snapshot, pending, pdbs=pdbs)
            if dc is None:
                assert fc is None and result is None, trial
            else:
                assert dc.node_name == fc.node_name \
                    == result.nominated_node_name, trial
                assert [p.metadata.name for p in dc.victims] \
                    == [p.metadata.name for p in fc.victims] \
                    == [p.metadata.name for p in result.victims], trial
                assert dc.num_pdb_violations == fc.num_pdb_violations, trial
                agree += 1
                if dc.num_pdb_violations:
                    saw_violations += 1
        assert agree >= 4
        assert saw_violations >= 1

    def test_device_pdb_partial_budget_order(self):
        """The directed allowance-consumption-ORDER pin, through the
        device rung: violating victims evict FIRST."""
        nodes = [make_node("n0", cpu="4", memory="16Gi", pods=110)]
        specs = [("p0", 0, 5.0), ("p1", 10, 1.0), ("p2", 10, 3.0),
                 ("p3", 5, 2.0)]
        pods = []
        for name, prio, start in specs:
            p = make_pod(name, cpu="900m", node_name="n0", priority=prio,
                         labels={"app": "db"})
            p.status.start_time = start
            pods.append(p)
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="db-pdb", namespace="default"),
            spec=v1.PodDisruptionBudgetSpec(
                selector=v1.LabelSelector(match_labels={"app": "db"})),
            status=v1.PodDisruptionBudgetStatus(disruptions_allowed=2),
        )
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("high", cpu="3900m", priority=100)
        dp, (dc,) = _device_plan(
            snapshot, [pending], _mk_backend(nodes, pods), pdbs=[pdb])
        assert dp.planner_paths == ["device"]
        assert dc is not None
        assert [p.metadata.name for p in dc.victims] == \
            ["p3", "p0", "p1", "p2"]
        assert dc.num_pdb_violations == 2

    def test_three_way_with_nominated_load(self):
        """A nominated ghost consumes capacity on its node through the
        framework's two-pass filter; the device rung must see it."""
        rng = random.Random(11)
        checked = 0
        for trial in range(12):
            nodes, pods = _random_cluster(rng, rng.randint(2, 6))
            snapshot = Snapshot.from_objects(pods, nodes)
            backend = _mk_backend(nodes, pods)
            nominator = PodNominator()
            ghost = make_pod("ghost", cpu="2", memory="1Gi", priority=100)
            nominator.add_nominated_pod(
                ghost, nodes[rng.randrange(len(nodes))].metadata.name
            )
            pending = make_pod("high", cpu="2500m", memory="1Gi",
                               priority=100)
            dp, (dc,) = _device_plan(
                snapshot, [pending], backend, nominator=nominator)
            fp = FastPreemptionPlanner(snapshot, nominator)
            (fc,) = fp.plan([pending])
            assert dp.fits_now == fp.fits_now, trial
            if dp.fits_now[0]:
                continue
            if dc is None:
                assert fc is None, trial
            else:
                assert fc is not None, trial
                assert dc.node_name == fc.node_name, trial
                assert [p.metadata.name for p in dc.victims] == [
                    p.metadata.name for p in fc.victims
                ], trial
                checked += 1
        assert checked >= 2


class TestDeviceEnvelope:
    """The capability extension: preemptors with pod (anti-)affinity and
    topology-spread constraints plan on the DEVICE rung — fast_eligible
    rejects them — and must match the oracle exactly."""

    def _anti_hostname(self, sel_labels):
        return v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(match_labels=sel_labels),
                    topology_key="kubernetes.io/hostname",
                )
            ]
        ))

    def _check_oracle(self, nodes, pods, pending, pdbs=None):
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = _mk_backend(nodes, pods)
        assert not fast_eligible(
            pending, snapshot, pdbs or [], []
        ) or pending.spec.topology_spread_constraints is None
        dp, (dc,) = _device_plan(
            snapshot, [pending], backend, nominator=PodNominator(),
            pdbs=pdbs)
        assert dp.planner_paths == ["device"], dp.planner_paths
        result, _ = _post_filter(snapshot, pending, pdbs=pdbs or [])
        if dp.fits_now[0]:
            return "fits", dc, result
        if dc is None:
            assert result is None
            return "none", dc, result
        assert result is not None
        assert dc.node_name == result.nominated_node_name
        assert sorted(p.metadata.name for p in dc.victims) == sorted(
            p.metadata.name for p in result.victims
        )
        return "cand", dc, result

    def test_anti_affinity_preemptor_evicts_repeller(self):
        """The preemptor's own required anti-affinity term matches a
        victim: evicting it clears the node — a candidate the numpy
        envelope can never produce."""
        nodes = [make_node("n0", cpu="4", pods=10, labels={"zone": "z0"})]
        pods = [make_pod("vx", cpu="3500m", node_name="n0", priority=1,
                         labels={"app": "x"})]
        pending = make_pod("hi", cpu="1", priority=100,
                           affinity=self._anti_hostname({"app": "x"}))
        assert not fast_eligible(
            pending, Snapshot.from_objects(pods, nodes), [], [])
        anti = WaveAntiTerms(Snapshot.from_objects(pods, nodes))
        assert device_eligible(pending, [], anti)
        outcome, dc, _ = self._check_oracle(nodes, pods, pending)
        assert outcome == "cand"
        assert [p.metadata.name for p in dc.victims] == ["vx"]

    def test_affinity_preemptor_base_state_semantics(self):
        """A required-affinity preemptor whose term pods are all
        lower-priority: the oracle's base state (every victim removed)
        breaks the affinity, so NO candidate — the anti-monotone case
        the reprieve order makes observable. Parity, not intuition, is
        the contract."""
        aff = v1.Affinity(pod_affinity=v1.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "y"}),
                    topology_key="zone",
                )
            ]
        ))
        nodes = [make_node("n0", cpu="4", pods=10, labels={"zone": "z0"})]
        pods = [
            make_pod("vy", cpu="1900m", node_name="n0", priority=1,
                     labels={"app": "y"}),
            make_pod("vz", cpu="1900m", node_name="n0", priority=1,
                     labels={"app": "z"}),
        ]
        pending = make_pod("hi", cpu="1900m", priority=100, affinity=aff)
        outcome, _, _ = self._check_oracle(nodes, pods, pending)
        assert outcome == "none"

    def test_affinity_preemptor_anchor_survives(self):
        """Same shape but the affinity anchor outranks the preemptor
        (never a victim): base feasibility holds, the filler evicts,
        and the reprieve keeps the anchor's zone count intact."""
        aff = v1.Affinity(pod_affinity=v1.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "y"}),
                    topology_key="zone",
                )
            ]
        ))
        nodes = [make_node("n0", cpu="4", pods=10, labels={"zone": "z0"})]
        pods = [
            make_pod("anchor", cpu="1900m", node_name="n0", priority=200,
                     labels={"app": "y"}),
            make_pod("vz", cpu="1900m", node_name="n0", priority=1,
                     labels={"app": "z"}),
        ]
        pending = make_pod("hi", cpu="1900m", priority=100, affinity=aff)
        outcome, dc, _ = self._check_oracle(nodes, pods, pending)
        assert outcome == "cand"
        assert [p.metadata.name for p in dc.victims] == ["vz"]

    def test_spread_preemptor(self):
        """DoNotSchedule maxSkew=1 on zone: the what-if must re-derive
        the global min count per candidate (evictions on the candidate
        can lower it) to pick the right node."""
        nodes = [
            make_node("n0", cpu="4", pods=10, labels={"zone": "z0"}),
            make_node("n1", cpu="4", pods=10, labels={"zone": "z1"}),
        ]
        pods = [
            make_pod("s0", cpu="3700m", node_name="n0", priority=1,
                     labels={"app": "s"}),
            make_pod("s1", cpu="500m", node_name="n1", priority=1,
                     labels={"app": "s"}),
            make_pod("f1", cpu="3300m", node_name="n1", priority=1,
                     labels={"app": "f"}),
        ]
        pending = make_pod("hi", cpu="1", priority=100,
                           labels={"app": "s"})
        pending.spec.topology_spread_constraints = [
            v1.TopologySpreadConstraint(
                max_skew=1, topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=v1.LabelSelector(
                    match_labels={"app": "s"}),
            )
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        assert not fast_eligible(pending, snapshot, [], [])
        outcome, dc, _ = self._check_oracle(nodes, pods, pending)
        assert outcome == "cand"
        assert dc.node_name == "n0"
        assert [p.metadata.name for p in dc.victims] == ["s0"]

    def test_spread_fuzz_vs_oracle(self):
        """Randomized spread-preemptor clusters (zones, mixed labels)
        against the oracle."""
        rng = random.Random(91)
        agree = 0
        for trial in range(12):
            zones = [f"z{i}" for i in range(rng.randint(2, 3))]
            nodes = [
                make_node(f"n{i}", cpu=str(rng.choice([2, 4])), pods=8,
                          labels={"zone": zones[i % len(zones)]})
                for i in range(rng.randint(3, 6))
            ]
            pods = []
            for i, node in enumerate(nodes):
                for j in range(rng.randint(1, 3)):
                    pods.append(make_pod(
                        f"p{i}-{j}",
                        cpu=f"{rng.choice([900, 1500, 1900])}m",
                        node_name=node.metadata.name,
                        priority=rng.choice([0, 1, 5]),
                        labels={"app": rng.choice(["s", "t"])},
                    ))
            pending = make_pod("hi", cpu="1500m", priority=100,
                               labels={"app": "s"})
            pending.spec.topology_spread_constraints = [
                v1.TopologySpreadConstraint(
                    max_skew=1, topology_key="zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "s"}),
                )
            ]
            snapshot = Snapshot.from_objects(pods, nodes)
            backend = _mk_backend(nodes, pods)
            dp, (dc,) = _device_plan(
                snapshot, [pending], backend, nominator=PodNominator())
            assert dp.planner_paths == ["device"], trial
            if dp.fits_now[0]:
                continue
            result, _ = _post_filter(snapshot, pending)
            if dc is None:
                assert result is None, trial
            else:
                assert result is not None, trial
                assert dc.node_name == result.nominated_node_name, trial
                assert sorted(
                    p.metadata.name for p in dc.victims
                ) == sorted(p.metadata.name for p in result.victims), trial
                agree += 1
        assert agree >= 2

    def test_device_eligibility_gates(self):
        nodes = [make_node("n0")]
        snapshot = Snapshot.from_objects([], nodes)
        anti = WaveAntiTerms(snapshot)
        spread = make_pod("p", cpu="1", priority=10)
        spread.spec.topology_spread_constraints = [
            v1.TopologySpreadConstraint(
                max_skew=1, topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
            )
        ]
        # affinity/spread are INSIDE the device envelope
        assert device_eligible(spread, [], anti)
        aff_pod = make_pod("p2", cpu="1", priority=10,
                           affinity=self._anti_hostname({"a": "b"}))
        assert device_eligible(aff_pod, [], anti)
        # extenders / Never / matched existing-anti stay outside
        assert not device_eligible(spread, [object()], anti)
        never = make_pod("p3", cpu="1", priority=10)
        never.spec.preemption_policy = "Never"
        assert not device_eligible(never, [], anti)
        anti_pod = make_pod(
            "anti", cpu="1", node_name="n0",
            affinity=self._anti_hostname({"app": "x"}),
        )
        snapshot2 = Snapshot.from_objects([anti_pod], nodes)
        anti2 = WaveAntiTerms(snapshot2)
        matched = make_pod("pm", cpu="1", priority=10,
                           labels={"app": "x"})
        assert not device_eligible(matched, [], anti2)


class TestDeviceWave:
    def test_wave_distinct_victims_shared_books(self):
        """A device-planned wave claims distinct victims and matches the
        pure-fast wave bit for bit (shared books across rungs)."""
        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(8)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(8) for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(8)
        ]
        dp, cands = _device_plan(
            snapshot, wave, _mk_backend(nodes, pods),
            nominator=PodNominator())
        assert dp.planner_paths == ["device"] * 8
        assert all(c is not None for c in cands)
        vk = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(vk) == len(set(vk)), "victim claimed twice"
        fp = FastPreemptionPlanner(snapshot, PodNominator())
        fcands = fp.plan([
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(8)
        ])
        assert [
            (c.node_name, sorted(p.metadata.name for p in c.victims))
            for c in cands
        ] == [
            (c.node_name, sorted(p.metadata.name for p in c.victims))
            for c in fcands
        ]

    def test_wave_saturates_then_fails(self):
        nodes = [make_node("n0", cpu="4", pods=10)]
        pods = [
            make_pod(f"low{j}", cpu="1900m", memory="64Mi",
                     node_name="n0", priority=1)
            for j in range(2)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="1900m", memory="64Mi", priority=100)
            for k in range(4)
        ]
        dp, cands = _device_plan(
            snapshot, wave, _mk_backend(nodes, pods),
            nominator=PodNominator())
        assert sum(1 for c in cands if c is not None) == 2
        assert sum(1 for c in cands if c is None) == 2

    def test_mixed_rung_wave_shares_books(self):
        """Half the wave rides the device rung, half the fast rung (per
        eligibility): no victim is claimed by both."""
        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(4)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(4) for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(6)
        ]
        elig = {
            v1.pod_key(p): ((k % 2 == 0), True)
            for k, p in enumerate(wave)
        }
        planner = DevicePreemptionPlanner(
            snapshot, PodNominator(), _mk_backend(nodes, pods),
            eligibility=elig,
        )
        cands = planner.plan(wave)
        assert planner.planner_paths == [
            "device", "fast", "device", "fast", "device", "fast"
        ]
        assert all(c is not None for c in cands)
        vk = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(vk) == len(set(vk))


class TestDeviceLadder:
    def test_kill_switch_falls_to_fast(self, monkeypatch):
        nodes = [make_node("n0", cpu="4", pods=10)]
        pods = [make_pod("low", cpu="3500m", node_name="n0", priority=1)]
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = _mk_backend(nodes, pods)
        backend.whatif = False  # KTPU_WHATIF=0
        pending = make_pod("hi", cpu="2", priority=100)
        dp, (dc,) = _device_plan(snapshot, [pending], backend, fast_ok=True)
        assert dp.planner_paths == ["fast"]
        assert dc is not None and dc.node_name == "n0"

    def test_injected_fault_falls_to_fast_no_double_claim(self):
        """raise-whatif mid-wave: the faulted pod falls to the fast
        rung on the SAME books — candidates stay disjoint and the live
        session is not invalidated."""
        from kubernetes_tpu.scheduler.metrics import session_rebuilds
        from kubernetes_tpu.testing.faults import FaultInjector

        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(3)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(3) for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = _mk_backend(nodes, pods)
        inj = FaultInjector()
        inj.arm("raise-whatif", shots=1)
        backend.faults = inj
        r0 = sum(v for _, v in session_rebuilds.items())
        wave = [
            make_pod(f"hi-{k}", cpu="900m", memory="64Mi", priority=100)
            for k in range(3)
        ]
        dp, cands = _device_plan(
            snapshot, wave, backend, nominator=PodNominator(),
            fast_ok=True)
        # first pod faulted -> fast; the rest ride the device rung
        assert dp.planner_paths == ["fast", "device", "device"]
        assert inj.injected.get("raise-whatif") == 1
        assert all(c is not None for c in cands)
        vk = [v1.pod_key(v) for c in cands for v in c.victims]
        assert len(vk) == len(set(vk)), "double-claimed victim"
        assert sum(v for _, v in session_rebuilds.items()) == r0

    def test_fault_on_device_only_pod_falls_to_oracle_sentinel(self):
        from kubernetes_tpu.scheduler.preemption_device import (
            ORACLE_FALLBACK,
        )
        from kubernetes_tpu.testing.faults import FaultInjector

        nodes = [make_node("n0", cpu="4", pods=10)]
        pods = [make_pod("low", cpu="3500m", node_name="n0", priority=1,
                         labels={"app": "x"})]
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = _mk_backend(nodes, pods)
        inj = FaultInjector()
        inj.arm("raise-whatif", shots=1)
        backend.faults = inj
        pending = make_pod(
            "hi", cpu="2", priority=100,
            affinity=v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[
                    v1.PodAffinityTerm(
                        label_selector=v1.LabelSelector(
                            match_labels={"app": "x"}),
                        topology_key="kubernetes.io/hostname",
                    )
                ]
            )),
        )
        dp, (dc,) = _device_plan(snapshot, [pending], backend)
        assert dc is ORACLE_FALLBACK
        assert dp.planner_paths == ["oracle"]
        assert dp.fits_now == [False]

    def test_live_session_scratch_snapshot(self):
        """With a live HoistedSession holding the preemptor's template,
        the what-if context snapshots ITS carry (no encoding upload) and
        planning never invalidates the session."""
        from kubernetes_tpu.ops.hoisted import HoistedSession
        from kubernetes_tpu.scheduler.metrics import session_rebuilds

        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(4)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(4) for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = _mk_backend(nodes, pods)
        probe = make_pod("probe", cpu="900m", memory="64Mi", priority=100)
        (res,) = backend.schedule_many([probe])
        assert res[1] is None  # saturated by design
        sess = backend._session
        assert isinstance(sess, HoistedSession)
        r0 = sum(v for _, v in session_rebuilds.items())
        pending = make_pod("hi", cpu="900m", memory="64Mi", priority=100)
        dp, (dc,) = _device_plan(
            snapshot, [pending], backend, nominator=PodNominator())
        assert dp.planner_paths == ["device"]
        assert dc is not None
        ctx = backend.whatif_context({
            k: v for k, v in backend.pe.encode(pending).items()
            if not k.startswith("_")
        })
        assert ctx._sess is backend._session
        assert backend._session is sess  # never torn down
        assert sum(v for _, v in session_rebuilds.items()) == r0
        # parity against the oracle from the same state
        result, _ = _post_filter(snapshot, pending)
        assert result is not None
        assert dc.node_name == result.nominated_node_name

    def test_pallas_session_routes_through_encoding_snapshot(self):
        """A live PallasSession keeps its carry in a kernel-private
        scaled layout; the what-if context must build from the
        non-donating encoding snapshot instead (construction-level on
        CPU — no pallas kernel run), leave the session untouched, and
        still match the oracle."""
        from kubernetes_tpu.ops.pallas_scan import PallasSession
        from kubernetes_tpu.scheduler.metrics import session_rebuilds

        nodes = [make_node(f"n{i}", cpu="4", pods=10) for i in range(3)]
        pods = [
            make_pod(f"low-{i}-{j}", cpu="900m", memory="64Mi",
                     node_name=f"n{i}", priority=1)
            for i in range(3) for j in range(4)
        ]
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = _mk_backend(nodes, pods)
        pending = make_pod("hi", cpu="900m", memory="64Mi", priority=100)
        pa = {
            k: v for k, v in backend.pe.encode(pending).items()
            if not k.startswith("_")
        }
        sess = PallasSession(
            backend.enc.scratch_state(), [pa], multipod_k=1)
        backend._session = sess
        r0 = sum(v for _, v in session_rebuilds.items())
        dp, (dc,) = _device_plan(
            snapshot, [pending], backend, nominator=PodNominator())
        assert dp.planner_paths == ["device"]
        ctx = backend.whatif_context(pa)
        assert ctx._sess is not sess  # encoding-based scratch view
        assert backend._session is sess  # live session untouched
        assert sum(v for _, v in session_rebuilds.items()) == r0
        result, _ = _post_filter(snapshot, pending)
        assert dc is not None and result is not None
        assert dc.node_name == result.nominated_node_name
        assert sorted(p.metadata.name for p in dc.victims) == sorted(
            p.metadata.name for p in result.victims
        )


# -- gang-aware preemption: whole gangs or none ------------------------------


class TestGangVictimParity:
    """Gang-aware victim selection across all three planner rungs:
    co-located gang members are one indivisible eviction unit (whole
    gangs or none), a gang with any member at-or-above the preemptor's
    priority is untouchable (never loses a prefix), and the fast and
    device rungs stay bit-identical to the oracle with gang units in
    the victim pool."""

    @staticmethod
    def _stamp(pod, group, size):
        from kubernetes_tpu.scheduler.plugins.coscheduling import (
            GROUP_LABEL,
            MIN_AVAILABLE_LABEL,
        )

        pod.metadata.annotations = {
            GROUP_LABEL: group,
            MIN_AVAILABLE_LABEL: str(size),
        }

    def _random_gang_cluster(self, rng: random.Random, n_nodes: int):
        """Mostly-saturated nodes where part of the load is co-located
        gangs: evictable gangs (every member below the preemptor),
        MIXED gangs (one member outranks it — untouchable whole), and
        plain singletons, never oversubscribing a node."""
        nodes, pods = [], []
        gangs = {}
        for i in range(n_nodes):
            cap = rng.choice([4000, 8000])
            nodes.append(make_node(
                f"n{i}", cpu=f"{cap}m", memory="16Gi", pods=110))
            used = 0
            if rng.random() < 0.7:
                size = rng.randint(2, 3)
                group = f"gang-n{i}"
                mixed = rng.random() < 0.3
                members = []
                for j in range(size):
                    prio = 200 if (mixed and j == 0) else \
                        rng.choice([0, 1, 5, 50])
                    p = make_pod(
                        f"g{i}-{j}", cpu="900m", memory="256Mi",
                        node_name=f"n{i}", priority=prio,
                    )
                    self._stamp(p, group, size)
                    pods.append(p)
                    members.append(p.metadata.name)
                    used += 900
                gangs[group] = (members, mixed)
            while True:
                req = rng.choice([900, 1500, 2000])
                if used + req > cap - 500:
                    break
                pods.append(make_pod(
                    f"p{i}-{used}", cpu=f"{req}m",
                    memory=rng.choice(["64Mi", "512Mi"]),
                    node_name=f"n{i}",
                    priority=rng.choice([0, 1, 5, 50]),
                ))
                used += req
        return nodes, pods, gangs

    @staticmethod
    def _assert_whole_gangs(victims, gangs, trial):
        names = {p.metadata.name for p in victims}
        whole = 0
        for group, (members, mixed) in gangs.items():
            took = names & set(members)
            if mixed:
                assert not took, (
                    f"trial {trial}: mixed gang {group} lost members "
                    f"{sorted(took)}"
                )
            else:
                assert took in (set(), set(members)), (
                    f"trial {trial}: gang {group} torn — evicted "
                    f"{sorted(took)} of {members}"
                )
                if took:
                    whole += 1
        return whole

    def test_three_way_whole_gang_or_none_fuzz(self):
        rng = random.Random(19)
        agree = none = gang_evictions = 0
        for trial in range(30):
            nodes, pods, gangs = self._random_gang_cluster(
                rng, rng.randint(3, 9))
            snapshot = Snapshot.from_objects(pods, nodes)
            backend = _mk_backend(nodes, pods)
            pending = make_pod(
                "high",
                cpu=f"{rng.choice([2500, 3500, 9000])}m",
                memory="1Gi", priority=100,
            )
            dp, (dc,) = _device_plan(
                snapshot, [pending], backend, nominator=PodNominator())
            assert dp.planner_paths == ["device"], (trial, dp.planner_paths)
            fp = FastPreemptionPlanner(snapshot, PodNominator())
            (fc,) = fp.plan([pending])
            assert dp.fits_now == fp.fits_now, trial
            if dp.fits_now[0]:
                continue
            result, _ = _post_filter(snapshot, pending)
            if dc is None:
                assert fc is None and result is None, trial
                none += 1
                continue
            assert fc is not None and result is not None, trial
            assert dc.node_name == fc.node_name \
                == result.nominated_node_name, trial
            assert [p.metadata.name for p in dc.victims] == [
                p.metadata.name for p in fc.victims
            ], trial
            assert sorted(p.metadata.name for p in dc.victims) == sorted(
                p.metadata.name for p in result.victims
            ), trial
            agree += 1
            for plan_victims in (dc.victims, fc.victims, result.victims):
                whole = self._assert_whole_gangs(plan_victims, gangs, trial)
            gang_evictions += whole
        # the fuzz must exercise agreement, no-candidate clusters, AND
        # actual whole-gang evictions
        assert agree >= 5, agree
        assert none >= 1, none
        assert gang_evictions >= 2, gang_evictions

    def test_mixed_gang_never_loses_a_prefix(self):
        """Directed: the only way to fit the preemptor is through a
        gang with one protected member — every rung must refuse (the
        pre-unit planners evicted the two low members: a torn gang)."""
        nodes = [make_node("n0", cpu="4", memory="16Gi", pods=110)]
        pods = []
        for j, prio in enumerate([200, 1, 1]):
            p = make_pod(f"g0-{j}", cpu="1200m", memory="256Mi",
                         node_name="n0", priority=prio)
            self._stamp(p, "gang-x", 3)
            pods.append(p)
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("high", cpu="2", memory="1Gi", priority=100)
        (fc,) = FastPreemptionPlanner(snapshot, PodNominator()).plan(
            [pending])
        assert fc is None
        dp, (dc,) = _device_plan(
            snapshot, [pending], _mk_backend(nodes, pods),
            nominator=PodNominator())
        assert dc is None
        result, _ = _post_filter(snapshot, pending)
        assert result is None

    def test_gang_unit_evicts_whole_even_when_one_member_suffices(self):
        """Directed: capacity-wise one gang member would be enough, but
        the unit is indivisible — all rungs evict the whole gang, and
        agree."""
        nodes = [make_node("n0", cpu="4", memory="16Gi", pods=110)]
        pods = []
        for j in range(2):
            p = make_pod(f"g0-{j}", cpu="1500m", memory="256Mi",
                         node_name="n0", priority=1)
            self._stamp(p, "gang-y", 2)
            pods.append(p)
        snapshot = Snapshot.from_objects(pods, nodes)
        pending = make_pod("high", cpu="2", memory="1Gi", priority=100)
        (fc,) = FastPreemptionPlanner(snapshot, PodNominator()).plan(
            [pending])
        assert fc is not None
        assert sorted(p.metadata.name for p in fc.victims) == \
            ["g0-0", "g0-1"]
        dp, (dc,) = _device_plan(
            snapshot, [pending], _mk_backend(nodes, pods),
            nominator=PodNominator())
        assert dc is not None
        assert [p.metadata.name for p in dc.victims] == [
            p.metadata.name for p in fc.victims
        ]
        result, _ = _post_filter(snapshot, pending)
        assert result is not None
        assert sorted(p.metadata.name for p in result.victims) == \
            ["g0-0", "g0-1"]
