"""metrics.k8s.io: MetricsServer scrape loop, kubectl top, HPA wired to
the metrics API (the metrics-server + HPA + top integration).
"""

import io

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.api.metrics import MetricsServer, pod_metrics_source
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.kubectl import Kubectl

from .util import make_node, make_pod


def _running(pod):
    pod.status.phase = "Running"
    return pod


class TestMetricsServer:
    def test_scrape_and_top(self):
        api = APIServer()
        cs = Clientset(api)
        cs.nodes.create(make_node("n1"))
        cs.nodes.create(make_node("n2"))
        cs.pods.create(_running(make_pod("a", cpu="200m", memory="64Mi", node_name="n1")))
        cs.pods.create(_running(make_pod("b", cpu="300m", memory="128Mi", node_name="n1")))
        cs.pods.create(_running(make_pod("c", cpu="100m", memory="32Mi", node_name="n2")))
        ms = MetricsServer(cs)
        ms.scrape_once()
        nm = cs.resource("nodemetrics").get("n1")
        assert nm.usage["cpu"] == "500m"
        pm = cs.resource("podmetrics").get("a", "default")
        assert pm.containers[0].usage["cpu"] == "200m"

        out = io.StringIO()
        k = Kubectl(cs, out=out)
        assert k.run(["top", "nodes"]) == 0
        lines = out.getvalue().strip().splitlines()
        assert lines[0].split() == ["NAME", "CPU(cores)", "MEMORY(bytes)"]
        assert "500m" in lines[1] and "192Mi" in lines[1]
        out.truncate(0), out.seek(0)
        assert k.run(["top", "pods"]) == 0
        assert "300m" in out.getvalue()

        # pod deleted -> its metrics are pruned on the next scrape
        cs.pods.delete("a", "default")
        ms.scrape_once()
        import pytest

        from kubernetes_tpu.apiserver.server import NotFound

        with pytest.raises(NotFound):
            cs.resource("podmetrics").get("a", "default")

    def test_hpa_reads_metrics_api(self):
        from kubernetes_tpu.api import apps
        from kubernetes_tpu.controllers.podautoscaler import HorizontalController

        api = APIServer()
        cs = Clientset(api)
        cs.deployments.create(
            apps.Deployment(
                metadata=v1.ObjectMeta(name="web", namespace="default"),
                spec=apps.DeploymentSpec(
                    replicas=2,
                    selector=v1.LabelSelector(match_labels={"app": "web"}),
                    template=v1.PodTemplateSpec(
                        metadata=v1.ObjectMeta(labels={"app": "web"}),
                        spec=v1.PodSpec(
                            containers=[v1.Container(name="c", image="i")]
                        ),
                    ),
                ),
            )
        )
        for i in range(2):
            cs.pods.create(
                _running(
                    make_pod(f"web-{i}", cpu="100m", labels={"app": "web"}, node_name="n1")
                )
            )
        # usage = 2x requests -> utilization 200% of the 80% target
        ms = MetricsServer(
            cs, usage_fn=lambda pod: {"cpu": "200m", "memory": "0"}
        )
        ms.scrape_once()
        from kubernetes_tpu.api.autoscaling import (
            CrossVersionObjectReference,
            HorizontalPodAutoscaler,
            HorizontalPodAutoscalerSpec,
        )

        cs.resource("horizontalpodautoscalers").create(
            HorizontalPodAutoscaler(
                metadata=v1.ObjectMeta(name="hpa", namespace="default"),
                spec=HorizontalPodAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="Deployment", name="web"
                    ),
                    max_replicas=8,
                    target_cpu_utilization_percentage=80,
                ),
            )
        )
        factory = SharedInformerFactory(cs)
        ctrl = HorizontalController(cs, factory, metrics=pod_metrics_source(cs))
        ctrl.sync_all()
        dep = cs.deployments.get("web", "default")
        assert dep.spec.replicas == 5  # ceil(2 * 200/80)
