"""CRI streaming protocols: interactive exec, attach, port-forward.

Reference: staging/src/k8s.io/kubelet/pkg/cri/streaming (the kubelet's
streaming server behind Exec/Attach/PortForward URLs, proxied by the
apiserver's remotecommand path)."""

import pytest

from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.clientset import Clientset
from kubernetes_tpu.kubelet.cri import CRIError, FakeRuntimeService
from kubernetes_tpu.kubelet.streaming import StreamSession

from .util import FAST_KUBELET, wait_until


class TestRuntimeStreams:
    def _running(self):
        rt = FakeRuntimeService()
        sb = rt.run_pod_sandbox("web", "default", "uid-1")
        cid = rt.create_container(sb, "app", "img:1")
        rt.start_container(cid)
        return rt, sb, cid

    def test_exec_stream_one_shot(self):
        rt, _, cid = self._running()
        s = rt.exec_stream(cid, ["echo", "hello", "world"])
        assert s.read_all() == b"hello world\n"
        assert s.exit_code == 0

    def test_exec_stream_interactive(self):
        rt, _, cid = self._running()
        s = rt.exec_stream(cid, ["sh"])
        s.write_stdin(b"first\n")
        assert s.read_stdout(timeout=5) == b"app> first\n"
        s.write_stdin(b"second\n")
        assert s.read_stdout(timeout=5) == b"app> second\n"
        s.close_stdin()
        assert s.read_stdout(timeout=5) is None  # clean EOF
        assert s.exit_code == 0

    def test_exec_stream_requires_running(self):
        rt, _, cid = self._running()
        rt.stop_container(cid)
        with pytest.raises(CRIError):
            rt.exec_stream(cid, ["sh"])

    def test_attach_follows_output(self):
        rt, _, cid = self._running()
        s = rt.attach_container(cid)
        # replayed start line arrives...
        first = s.read_stdout(timeout=5)
        assert b"starting app" in first
        # ...and new output is followed live
        rt.stop_container(cid, exit_code=0)
        chunks = []
        while True:
            c = s.read_stdout(timeout=5)
            if c is None:
                break
            chunks.append(c)
        assert any(b"exited with code 0" in c for c in chunks)

    def test_port_forward_round_trip(self):
        rt, sb, _ = self._running()
        rt.register_port_server(sb, 8080, lambda req: b"HTTP/1.1 200 " + req)
        s = rt.port_forward(sb, 8080)
        s.write_stdin(b"GET /")
        assert s.read_stdout(timeout=5) == b"HTTP/1.1 200 GET /"
        s.close_stdin()

    def test_port_forward_connection_refused(self):
        rt, sb, _ = self._running()
        with pytest.raises(CRIError):
            rt.port_forward(sb, 9999)


class TestStreamingThroughApiserver:
    """The full proxy chain: apiserver → node proxy → kubelet → CRI."""

    @pytest.fixture()
    def cluster(self):
        from kubernetes_tpu.kubemark import HollowCluster

        api = APIServer()
        cs = Clientset(api)
        hollow = HollowCluster(cs, n_nodes=1, config_overrides=FAST_KUBELET)
        hollow.start()
        yield api, cs, hollow
        hollow.stop()

    def _run_pod(self, api, cs, hollow):
        from .util import make_pod

        pod = make_pod("web", cpu="100m")
        node = hollow.kubelets[0].config.node_name
        pod.spec.node_name = node
        cs.pods.create(pod)
        assert wait_until(
            lambda: cs.pods.get("web", "default").status.phase == "Running",
            timeout=30,
        )
        return hollow.kubelets[0]

    def test_exec_stream_end_to_end(self, cluster):
        api, cs, hollow = cluster
        self._run_pod(api, cs, hollow)
        s = api.pod_exec_stream("web", "default", ["echo", "over-the-proxy"])
        assert s.read_all() == b"over-the-proxy\n"

    def test_attach_and_portforward_end_to_end(self, cluster):
        api, cs, hollow = cluster
        kubelet = self._run_pod(api, cs, hollow)
        attach = api.pod_attach("web", "default")
        assert b"starting" in attach.read_stdout(timeout=5)
        attach.close()

        for sb in kubelet.runtime.list_pod_sandboxes():
            if sb.pod_name == "web":
                kubelet.runtime.register_port_server(
                    sb.id, 80, lambda b: b"pong:" + b)
        pf = api.pod_portforward("web", "default", 80)
        pf.write_stdin(b"ping")
        assert pf.read_stdout(timeout=5) == b"pong:ping"
        pf.close_stdin()
