"""Round-2 admission plugins: ServiceAccount, NodeRestriction,
EventRateLimit (plugin/pkg/admission/{serviceaccount,noderestriction,
eventratelimit}).

The VERDICT criteria: hollow kubelets get default service-account tokens
mounted, and a kubelet cannot modify another node's objects."""

import pytest

from kubernetes_tpu.api import rbac
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.admission import (
    event_rate_limit,
    install_default_admission,
    node_restriction,
    service_account_admission,
)
from kubernetes_tpu.apiserver.auth import SecureAPIServer
from kubernetes_tpu.apiserver.server import APIServer, Invalid
from kubernetes_tpu.client.events import Event

from .util import make_node, make_pod


def _sa_fixture(api: APIServer):
    api.register_resource(
        __import__(
            "kubernetes_tpu.apiserver.server", fromlist=["ResourceInfo"]
        ).ResourceInfo("serviceaccounts", rbac.ServiceAccount, True)
    )
    api.create("serviceaccounts", rbac.ServiceAccount(
        metadata=v1.ObjectMeta(name="robot", namespace="default")))
    api.create("secrets", v1.Secret(
        metadata=v1.ObjectMeta(
            name="robot-token-abc12", namespace="default",
            annotations={v1.SERVICE_ACCOUNT_NAME_ANNOTATION: "robot"},
        ),
        type=v1.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN,
        data={"token": "tok"},
    ))


class TestServiceAccountAdmission:
    def test_defaults_sa_name_and_mounts_token(self):
        api = APIServer()
        _sa_fixture(api)
        api.create("secrets", v1.Secret(
            metadata=v1.ObjectMeta(
                name="default-token-xyz99", namespace="default",
                annotations={v1.SERVICE_ACCOUNT_NAME_ANNOTATION: "default"},
            ),
            type=v1.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN,
            data={"token": "dt"},
        ))
        admit = service_account_admission(api)
        pod = make_pod("p")
        admit("pods", "CREATE", pod)
        assert pod.spec.service_account_name == "default"
        sources = [
            (vol.source or {}).get("secret", {}).get("secretName")
            for vol in pod.spec.volumes or []
        ]
        assert "default-token-xyz99" in sources

    def test_named_sa_token_mounted(self):
        api = APIServer()
        _sa_fixture(api)
        admit = service_account_admission(api)
        pod = make_pod("p")
        pod.spec.service_account_name = "robot"
        admit("pods", "CREATE", pod)
        sources = [
            (vol.source or {}).get("secret", {}).get("secretName")
            for vol in pod.spec.volumes or []
        ]
        assert "robot-token-abc12" in sources

    def test_missing_named_sa_rejected(self):
        api = APIServer()
        admit = service_account_admission(api)
        pod = make_pod("p")
        pod.spec.service_account_name = "ghost"
        with pytest.raises(Invalid):
            admit("pods", "CREATE", pod)

    def test_automount_disabled(self):
        api = APIServer()
        _sa_fixture(api)
        admit = service_account_admission(api)
        pod = make_pod("p")
        pod.spec.service_account_name = "robot"
        pod.spec.automount_service_account_token = False
        admit("pods", "CREATE", pod)
        assert not pod.spec.volumes


class TestNodeRestriction:
    """Driven through the FULL secured chain so the thread-local identity
    plumbing (auth._gated -> requestcontext -> admission) is what's
    tested, not the plugin in isolation."""

    @pytest.fixture()
    def secure(self):
        s = SecureAPIServer()
        install_default_admission(s.api)
        # kubelet identities + a broad RBAC grant: NodeRestriction must
        # narrow what RBAC alone would allow (that's its whole point)
        for n in ("n1", "n2"):
            s.authenticator.add_token(f"kubelet-{n}", f"system:node:{n}",
                                      ["system:nodes"])
        s.api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name="node-broad"),
            rules=[rbac.PolicyRule(verbs=["*"], resources=["*"])]))
        s.api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name="node-broad"),
            subjects=[rbac.Subject(kind="Group", name="system:nodes")],
            role_ref=rbac.RoleRef(kind="ClusterRole", name="node-broad")))
        s.api.create("nodes", make_node("n1"))
        s.api.create("nodes", make_node("n2"))
        return s

    def test_kubelet_updates_own_node(self, secure):
        cs = secure.as_user("kubelet-n1")
        node = cs.nodes.get("n1")
        node.status.phase = "Running"
        cs.nodes.update_status(node)  # no raise

    def test_kubelet_cannot_update_other_node(self, secure):
        cs = secure.as_user("kubelet-n1")
        node = cs.nodes.get("n2")
        node.status.phase = "Hacked"
        with pytest.raises(Invalid):
            cs.nodes.update_status(node)

    def test_kubelet_cannot_touch_other_nodes_pods(self, secure):
        secure.api.create("pods", make_pod("on-n2", node_name="n2"))
        cs = secure.as_user("kubelet-n1")
        with pytest.raises(Invalid):
            cs.pods.delete("on-n2", "default")

    def test_kubelet_updates_own_pods(self, secure):
        secure.api.create("pods", make_pod("on-n1", node_name="n1"))
        cs = secure.as_user("kubelet-n1")
        pod = cs.pods.get("on-n1", "default")
        pod.status.phase = "Running"
        cs.pods.update_status(pod)  # no raise

    def test_kubelet_cannot_create_cluster_objects(self, secure):
        cs = secure.as_user("kubelet-n1")
        with pytest.raises(Invalid):
            cs.configmaps.create(v1.ConfigMap(
                metadata=v1.ObjectMeta(name="cm", namespace="default")))

    def test_in_proc_callers_unrestricted(self, secure):
        # loopback (no request user): controllers/scheduler paths
        secure.api.create("pods", make_pod("loopback", node_name="n2"))
        secure.api.delete("pods", "loopback", "default")


class TestEventRateLimit:
    def test_bucket_throttles(self):
        api = APIServer()
        admit = event_rate_limit(api, qps=10.0, burst=5)
        ev = Event(metadata=v1.ObjectMeta(name="e", namespace="default"))
        for _ in range(5):
            admit("events", "CREATE", ev)
        with pytest.raises(Invalid):
            admit("events", "CREATE", ev)

    def test_namespaces_isolated(self):
        api = APIServer()
        admit = event_rate_limit(api, qps=10.0, burst=2)
        a = Event(metadata=v1.ObjectMeta(name="e", namespace="a"))
        b = Event(metadata=v1.ObjectMeta(name="e", namespace="b"))
        admit("events", "CREATE", a)
        admit("events", "CREATE", a)
        with pytest.raises(Invalid):
            admit("events", "CREATE", a)
        admit("events", "CREATE", b)  # b's bucket untouched


class TestTokenMountE2E:
    def test_pod_gets_default_sa_token_mounted(self):
        """SA controller + token controller + ServiceAccount admission,
        end to end: a pod created in a fresh namespace mounts the default
        SA's token secret (the VERDICT r1 item-7 criterion)."""
        from kubernetes_tpu.client.clientset import Clientset
        from kubernetes_tpu.client.informer import SharedInformerFactory
        from kubernetes_tpu.controllers.serviceaccount import (
            ServiceAccountController,
            TokensController,
        )

        from .util import wait_until

        api = APIServer()
        install_default_admission(api)
        cs = Clientset(api)
        factory = SharedInformerFactory(cs)
        sa_ctrl = ServiceAccountController(cs, factory)
        tok_ctrl = TokensController(cs, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        sa_ctrl.run()
        tok_ctrl.run()
        try:
            cs.namespaces.create(v1.Namespace(
                metadata=v1.ObjectMeta(name="apps")))

            def token_ready():
                return any(
                    s.type == v1.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN
                    for s in cs.secrets.list(namespace="apps")[0]
                )

            assert wait_until(token_ready, timeout=10)
            pod = make_pod("worker", namespace="apps")
            created = cs.pods.create(pod)
            assert created.spec.service_account_name == "default"
            secret_names = [
                (vol.source or {}).get("secret", {}).get("secretName", "")
                for vol in created.spec.volumes or []
            ]
            assert any(n.startswith("default-token-") for n in secret_names)
        finally:
            tok_ctrl.stop()
            sa_ctrl.stop()
            factory.stop()
