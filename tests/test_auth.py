"""Authn + RBAC authz: the secured apiserver chain.

Reference shape: plugin/pkg/auth/authorizer/rbac tests (rule matching,
binding scope) + authentication token tests.
"""

import pytest

from kubernetes_tpu.api import rbac
from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver.auth import (
    Forbidden,
    SecureAPIServer,
    Unauthorized,
)

from .util import make_pod


@pytest.fixture()
def secure():
    s = SecureAPIServer()
    s.authenticator.add_token("admin-token", "admin", ["system:masters"])
    s.authenticator.add_token("dev-token", "dev")
    s.authenticator.add_token("viewer-token", "viewer")
    return s


def _grant(s, name, rules, subjects, namespace=None):
    if namespace:
        s.api.create("roles", rbac.Role(
            metadata=v1.ObjectMeta(name=name, namespace=namespace), rules=rules))
        s.api.create("rolebindings", rbac.RoleBinding(
            metadata=v1.ObjectMeta(name=name, namespace=namespace),
            subjects=subjects,
            role_ref=rbac.RoleRef(kind="Role", name=name)))
    else:
        s.api.create("clusterroles", rbac.ClusterRole(
            metadata=v1.ObjectMeta(name=name), rules=rules))
        s.api.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=v1.ObjectMeta(name=name),
            subjects=subjects,
            role_ref=rbac.RoleRef(kind="ClusterRole", name=name)))


class TestAuthn:
    def test_invalid_token(self, secure):
        with pytest.raises(Unauthorized):
            secure.as_user("nope")

    def test_masters_bypass(self, secure):
        cs = secure.as_user("admin-token")
        cs.pods.create(make_pod("p"))
        assert cs.pods.get("p", "default").metadata.name == "p"
        cs.nodes.list()


class TestRBAC:
    def test_default_deny(self, secure):
        cs = secure.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.pods.list(namespace="default")
        with pytest.raises(Forbidden):
            cs.pods.create(make_pod("p"))

    def test_namespace_scoped_role(self, secure):
        _grant(
            secure, "pod-editor",
            [rbac.PolicyRule(verbs=["get", "list", "create"], resources=["pods"])],
            [rbac.Subject(kind="User", name="dev")],
            namespace="default",
        )
        cs = secure.as_user("dev-token")
        cs.pods.create(make_pod("p"))
        assert cs.pods.get("p", "default")
        cs.pods.list(namespace="default")
        # other verbs/namespaces still denied
        with pytest.raises(Forbidden):
            cs.pods.delete("p", "default")
        with pytest.raises(Forbidden):
            cs.pods.list(namespace="other")
        # unrelated user denied
        with pytest.raises(Forbidden):
            secure.as_user("viewer-token").pods.list(namespace="default")

    def test_cluster_role_binding_grants_everywhere(self, secure):
        _grant(
            secure, "pod-reader",
            [rbac.PolicyRule(verbs=["get", "list", "watch"], resources=["pods"])],
            [rbac.Subject(kind="User", name="viewer")],
        )
        secure.api.create("namespaces", v1.Namespace(metadata=v1.ObjectMeta(name="other")))
        cs = secure.as_user("viewer-token")
        cs.pods.list(namespace="default")
        cs.pods.list(namespace="other")
        w = cs.pods.watch()
        w.stop()
        with pytest.raises(Forbidden):
            cs.pods.create(make_pod("p"))

    def test_wildcards_and_resource_names(self, secure):
        _grant(
            secure, "cm-one",
            [rbac.PolicyRule(verbs=["*"], resources=["configmaps"],
                             resource_names=["allowed"])],
            [rbac.Subject(kind="User", name="dev")],
            namespace="default",
        )
        cs = secure.as_user("dev-token")
        assert_raises_forbidden = pytest.raises(Forbidden)
        # resourceNames cannot gate create (no name yet at authz time in
        # the reference either — create with resourceNames is denied)
        with assert_raises_forbidden:
            cs.configmaps.create(
                v1.ConfigMap(metadata=v1.ObjectMeta(name="allowed", namespace="default"))
            )
        secure.api.create("configmaps", v1.ConfigMap(
            metadata=v1.ObjectMeta(name="allowed", namespace="default")))
        secure.api.create("configmaps", v1.ConfigMap(
            metadata=v1.ObjectMeta(name="secret", namespace="default")))
        assert cs.configmaps.get("allowed", "default")
        with pytest.raises(Forbidden):
            cs.configmaps.get("secret", "default")

    def test_api_group_scoping(self, secure):
        # a rule scoped to the apps group must NOT grant core resources
        _grant(
            secure, "apps-only",
            [rbac.PolicyRule(verbs=["*"], resources=["*"], api_groups=["apps"])],
            [rbac.Subject(kind="User", name="dev")],
        )
        cs = secure.as_user("dev-token")
        cs.deployments.list(namespace="default")  # apps/v1
        with pytest.raises(Forbidden):
            cs.pods.list(namespace="default")  # core ("")

    def test_group_subject(self, secure):
        secure.authenticator.add_token("t2", "eng-1", ["team:eng"])
        _grant(
            secure, "eng-nodes",
            [rbac.PolicyRule(verbs=["list"], resources=["nodes"])],
            [rbac.Subject(kind="Group", name="team:eng")],
        )
        secure.as_user("t2").nodes.list()

    def test_service_account_token(self, secure):
        secure.api.create("serviceaccounts", rbac.ServiceAccount(
            metadata=v1.ObjectMeta(name="ci", namespace="default")))
        token = secure.service_account_token("default", "ci")
        _grant(
            secure, "ci-jobs",
            [rbac.PolicyRule(verbs=["create"], resources=["jobs"],
                             api_groups=["batch"])],
            [rbac.Subject(kind="ServiceAccount", name="ci", namespace="default")],
            namespace="default",
        )
        from kubernetes_tpu.api import batch

        cs = secure.as_user(token)
        cs.jobs.create(batch.Job(metadata=v1.ObjectMeta(name="j", namespace="default")))
        with pytest.raises(Forbidden):
            cs.pods.list(namespace="default")


class TestExecLogsSubresources:
    """pods/log + pods/exec ride the secured chain (the reference gates
    them as subresources behind authorization and audits them;
    registry/core/pod/rest + the exec SPDY handshake authz)."""

    class _FakeKubeletAPI:
        def container_logs(self, name, namespace, container, tail):
            return ["line-1", "line-2"]

        def exec_in_pod(self, name, namespace, cmd, container):
            return "ok", 0

    def _scheduled_pod(self, secure):
        pod = make_pod("p")
        pod.spec.node_name = "n1"
        secure.api.create("pods", pod)
        secure.api.register_node_proxy("n1", self._FakeKubeletAPI())

    def test_exec_denied_without_subresource_grant(self, secure):
        self._scheduled_pod(secure)
        # full verbs on pods do NOT imply pods/exec (subresources are
        # distinct RBAC resources, as in the reference)
        _grant(secure, "pod-admin",
               [rbac.PolicyRule(verbs=["*"], resources=["pods"])],
               [rbac.Subject(kind="User", name="dev")])
        cs = secure.as_user("dev-token")
        with pytest.raises(Forbidden):
            cs.pod_exec("p", "default", ["true"])
        with pytest.raises(Forbidden):
            cs.pod_logs("p", "default")

    def test_exec_and_logs_with_grant(self, secure):
        self._scheduled_pod(secure)
        _grant(secure, "pod-debugger",
               [rbac.PolicyRule(verbs=["create"], resources=["pods/exec"]),
                rbac.PolicyRule(verbs=["get"], resources=["pods/log"])],
               [rbac.Subject(kind="User", name="dev")])
        cs = secure.as_user("dev-token")
        out, code = cs.pod_exec("p", "default", ["true"])
        assert (out, code) == ("ok", 0)
        assert cs.pod_logs("p", "default") == ["line-1", "line-2"]

    def test_exec_is_audited(self, secure):
        from kubernetes_tpu.apiserver.audit import AuditLogger

        self._scheduled_pod(secure)
        secure.audit = AuditLogger()
        cs = secure.as_user("admin-token")
        cs.pod_exec("p", "default", ["true"])
        events = secure.audit.events(resource="pods/exec")
        assert events, "exec must leave a forensic trail"
        assert any(e.verb == "create" for e in events)
