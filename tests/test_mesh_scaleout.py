"""Multi-host mesh scale-out: one scoring backend, 2/4/8-shard parity.

The sharded session (ops/sharded_scan.py) must be a pure performance
property — every subsystem that rides it (session carry deltas, the
multipod conflict-suffix contract, the what-if preemption planner)
stays BIT-IDENTICAL to the single-device reference at every shard
count, including mid-run node churn. And churn itself must stay
delta-class: node add/remove on pre-warmed vocab patches the live
session's node columns instead of tearing it down (the rebuild-storm
regression the 100k-node envelope depends on).

The 8-device mesh is simulated on CPU (tests/conftest.py forces
XLA_FLAGS=--xla_force_host_platform_device_count=8 before jax imports).
"""

from __future__ import annotations

import copy
import random

import numpy as np
import pytest

import jax

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.ops.hoisted import HoistedSession
from kubernetes_tpu.parallel.sharded import make_mesh
from kubernetes_tpu.scheduler import metrics
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend

from .util import make_node, make_pod


def _mesh_or_skip(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    return make_mesh(n_devices=n)


def _node(i, cpu="8", memory="32Gi"):
    return make_node(f"node-{i}", cpu=cpu, memory=memory,
                     labels={v1.LABEL_HOSTNAME: f"node-{i}"})


def _mk_backend(n_nodes, mesh=None, cpu="8"):
    cache = SchedulerCache()
    be = TPUBackend(mesh=mesh)
    cache.add_listener(be)
    for i in range(n_nodes):
        cache.add_node(_node(i, cpu=cpu))
    return cache, be


def _rebuilds(reasons):
    return sum(val for key, val in metrics.session_rebuilds.items()
               if key and key[0] in reasons)


def _pods(prefix, n, cpu="100m", memory="64Mi", seed=None):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        kw = {}
        if seed is not None:
            kw["cpu"] = f"{rng.choice([50, 100, 250, 500])}m"
            kw["memory"] = rng.choice(["64Mi", "256Mi", "1Gi"])
        else:
            kw["cpu"], kw["memory"] = cpu, memory
        out.append(make_pod(f"{prefix}-{i}", namespace="default",
                            labels={"app": prefix}, **kw))
    return out


# ------------------------------------------------- session-delta parity


class TestSessionDeltaParity:
    """Satellite: randomized pod stream scheduled through a mesh backend
    (ShardedPallasSession + KTPU_SESSION_DELTAS carry patches) vs the
    single-device hoisted backend — decisions must match pod for pod,
    with node churn injected mid-stream on the delta path."""

    @pytest.mark.parametrize("nsh", [2, 4, 8])
    def test_randomized_stream_parity(self, nsh, monkeypatch):
        mesh = _mesh_or_skip(nsh)
        monkeypatch.setenv("KTPU_SESSION_DELTAS", "1")
        monkeypatch.setenv("KTPU_NODE_HEADROOM", "0.5")

        def drive(use_mesh):
            cache, be = _mk_backend(10, mesh=mesh if use_mesh else None)
            got = []
            for batch in range(4):
                pods = _pods(f"b{batch}", 5, seed=1000 * nsh + batch)
                got += [n for _, n in be.schedule_many(pods)]
                if batch == 1 and use_mesh:
                    # churn mid-run on the DELTA path only: pre-warmed
                    # names, pod-free lanes -> the session must survive
                    # and keep emitting reference-identical decisions
                    sess = be._session
                    victims = [nm for nm in be.enc.node_names[::-1]
                               if nm and not any(n == nm for n in got)][:2]
                    for nm in victims:
                        cache.remove_node(nm)
                    # re-add LIFO (the tombstone free-stack order) so
                    # every node returns to its original lane: decisions
                    # are lane-ordered, so lane permutation would flip
                    # lowest-index tie-breaks — a different-but-valid
                    # schedule, not the bit-parity this test pins
                    for nm in reversed(victims):
                        num = int(nm.split("-")[1])
                        cache.add_node(_node(num))
                    assert be._session is sess, "churn tore the session"
            return got, type(be._session).__name__

        got, kind = drive(True)
        ref, ref_kind = drive(False)
        assert kind == "ShardedPallasSession"
        assert ref_kind == "HoistedSession"
        assert got == ref, f"nsh={nsh}: {got} != {ref}"

    def test_delta_patch_kinds_survive_churn(self, sim_mesh, monkeypatch):
        """The delta queue actually carries node-join/node-leave entries
        (not silently rebuilding), and flushing them through a schedule
        keeps parity with a fresh rebuild of the same encoding."""
        from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession

        monkeypatch.setenv("KTPU_SESSION_DELTAS", "1")
        cache, be = _mk_backend(12, mesh=sim_mesh)
        warm = _pods("warm", 4)
        got = [n for _, n in be.schedule_many(warm)]
        for nm in ("node-10", "node-11"):
            cache.remove_node(nm)
        cache.add_node(_node(10))
        kinds = [d["kind"] for d in be._deltas]
        assert kinds.count("node-leave") == 2
        assert kinds.count("node-join") == 1
        tail = _pods("tail", 6)
        got += [n for _, n in be.schedule_many(tail)]

        # reference: fresh sharded session over a fresh encoding that
        # saw the same final cluster state and the same committed pods
        ref_cache, ref_be = _mk_backend(12, mesh=sim_mesh)
        for nm in ("node-10", "node-11"):
            ref_cache.remove_node(nm)
        ref_cache.add_node(_node(10))
        ref = [n for _, n in ref_be.schedule_many(copy.deepcopy(warm))]
        ref += [n for _, n in ref_be.schedule_many(copy.deepcopy(tail))]
        assert isinstance(ref_be._session, ShardedPallasSession)
        assert got == ref


# --------------------------------------- multipod conflict-suffix parity


class TestConflictSuffixParity:
    """Satellite: the sharded multipod step's conflict-SUFFIX contract —
    flagged pods stay uncommitted and the host replays them — must
    land every pod exactly where the sequential reference does."""

    @pytest.mark.parametrize("nsh", [2, 4, 8])
    def test_directed_last_slot_race(self, nsh):
        from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession

        mesh = _mesh_or_skip(nsh)
        # node-0 fits ONE 2-cpu pod; two racing pods in one k=2 step
        cache, be = _mk_backend(2, cpu="3")
        cache.remove_node("node-1")
        cache.add_node(_node(1, cpu="1"))
        pods = [make_pod(f"race-{i}", namespace="default", cpu="2",
                         memory="128Mi", labels={"app": "race"})
                for i in range(2)]
        arrays = [{k: a for k, a in be.pe.encode(p).items()
                   if not k.startswith("_")} for p in pods]
        cluster = be.enc.device_state()
        ref = HoistedSession(cluster, [arrays[0]], be.weights, multipod_k=1)
        want = HoistedSession.decisions(ref.schedule(list(arrays)))
        assert want == [0, -1], f"reference surprised us: {want}"

        sess = ShardedPallasSession(
            cluster, [arrays[0]], be.weights, mesh=mesh, multipod_k=2)
        assert sess.multipod_k == 2
        ys = sess.schedule(list(arrays))
        got = ShardedPallasSession.decisions(ys)
        n_conf, suffix = ShardedPallasSession.conflict_stats(ys)
        assert n_conf >= 1, "last-slot race produced no conflict"
        assert suffix == 1, "conflict must head the uncommitted suffix"
        assert got[:suffix] == want[:suffix]
        # host-side replay of the suffix through the SAME session
        ys2 = sess.schedule([arrays[i] for i in range(suffix, 2)])
        replay = ShardedPallasSession.decisions(ys2)
        assert got[:suffix] + replay == want

    @pytest.mark.parametrize("nsh", [2, 4, 8])
    def test_backend_replays_suffix(self, nsh, monkeypatch):
        """End to end: schedule_many on a mesh backend with multipod
        enabled equals the sequential no-mesh reference, and the
        conflict actually flowed through the suffix-replay path."""
        mesh = _mesh_or_skip(nsh)
        monkeypatch.setenv("KTPU_MULTIPOD_K", "2")
        pods = [make_pod(f"race-{i}", namespace="default", cpu="2",
                         memory="128Mi", labels={"app": "race"})
                for i in range(4)]

        _, be = _mk_backend(3, mesh=mesh, cpu="3")
        r0 = sum(v for _, v in metrics.conflict_replays.items())
        got = [n for _, n in be.schedule_many(copy.deepcopy(pods))]
        assert sum(v for _, v in metrics.conflict_replays.items()) > r0, \
            "race group produced no conflict replay"

        monkeypatch.setenv("KTPU_MULTIPOD_K", "1")
        _, ref_be = _mk_backend(3, mesh=None, cpu="3")
        ref = [n for _, n in ref_be.schedule_many(copy.deepcopy(pods))]
        assert got == ref, f"nsh={nsh}: {got} != {ref}"


# ------------------------------------------------------- what-if parity


class TestWhatifParity:
    """Satellite: the device preemption planner's what-if context built
    over a sharded cluster (whatif.from_host_snapshot mesh path) plans
    the same victims as the single-device context and the oracle."""

    @pytest.mark.parametrize("nsh", [2, 4, 8])
    def test_preemption_plan_parity(self, nsh):
        from kubernetes_tpu.scheduler.framework.snapshot import Snapshot
        from kubernetes_tpu.scheduler.internal.nominator import PodNominator
        from kubernetes_tpu.scheduler.preemption_device import (
            DevicePreemptionPlanner,
        )

        from .test_preemption import _post_filter

        mesh = _mesh_or_skip(nsh)
        nodes = [_node(i, cpu="4", memory="16Gi") for i in range(5)]
        fills = [
            make_pod(f"low-{i}-{j}", namespace="default", cpu="900m",
                     memory="64Mi", labels={"app": "low"},
                     node_name=f"node-{i}", priority=1)
            for i in range(5) for j in range(4)
        ]
        snapshot = Snapshot.from_objects(fills, nodes)
        pending = make_pod("hi", namespace="default", cpu="900m",
                           memory="64Mi", labels={"app": "hi"},
                           priority=100)

        def plan(use_mesh):
            be = TPUBackend(mesh=mesh if use_mesh else None)
            be.whatif = True  # CPU default is off; tests opt in
            for n in nodes:
                be.on_add_node(n)
            for p in fills:
                be.on_add_pod(p, p.spec.node_name)
            planner = DevicePreemptionPlanner(
                snapshot, PodNominator(), be,
                eligibility={v1.pod_key(pending): (True, False)})
            (cand,) = planner.plan([pending])
            assert planner.planner_paths == ["device"]
            assert cand is not None
            return cand

        got = plan(True)
        ref = plan(False)
        oracle, _ = _post_filter(snapshot, pending)
        assert got.node_name == ref.node_name == oracle.nominated_node_name
        assert (sorted(p.metadata.name for p in got.victims)
                == sorted(p.metadata.name for p in ref.victims)
                == sorted(p.metadata.name for p in oracle.victims))


# ------------------------------------------------- rebuild-storm gates


class TestNodeChurnStorm:
    """Node add/remove churn with pre-warmed vocab must stay
    delta-class: the live sharded session is patched per-lane, never
    torn down, and decisions stay identical to the rebuild-everything
    control. Genuinely structural events (a never-seen node name) are
    the only allowed rebuilds."""

    def test_churn_stays_delta_class(self, sim_mesh, monkeypatch):
        monkeypatch.setenv("KTPU_SESSION_DELTAS", "1")
        monkeypatch.setenv("KTPU_NODE_HEADROOM", "0.5")

        def drive(delta_patching):
            cache, be = _mk_backend(20, mesh=sim_mesh)
            be.delta_patching = delta_patching
            got = [n for _, n in be.schedule_many(_pods("warm", 4))]
            sess = be._session
            r0 = _rebuilds({"node-add", "node-remove"})
            joins = 0
            for _ in range(3):
                for i in range(12, 16):
                    cache.remove_node(f"node-{i}")
                for i in range(12, 16):
                    cache.add_node(_node(i))
                    joins += 1
            alive = be._session is sess
            got += [n for _, n in be.schedule_many(_pods("after", 6))]
            return got, alive, _rebuilds({"node-add", "node-remove"}) - r0

        got, alive, churn = drive(True)
        ref, _, _ = drive(False)
        assert got == ref
        assert alive, "pre-warmed churn tore the session down"
        assert churn == 0, f"churn caused {churn} rebuilds"

    def test_structural_event_still_rebuilds(self, sim_mesh, monkeypatch):
        """A genuinely-new node name (vocab growth) must NOT be forced
        through the delta path — correctness beats session survival."""
        monkeypatch.setenv("KTPU_SESSION_DELTAS", "1")
        cache, be = _mk_backend(8, mesh=sim_mesh)
        got = [n for _, n in be.schedule_many(_pods("warm", 2))]
        cache.add_node(make_node(
            "brand-new-node", cpu="64", memory="256Gi",
            labels={v1.LABEL_HOSTNAME: "brand-new-node"}))
        got += [n for _, n in be.schedule_many(
            _pods("big", 1, cpu="32", memory="128Gi"))]
        assert got[-1] == "brand-new-node"

    @pytest.mark.slow
    def test_storm_20k_nodes_1000_events(self, sim_mesh, monkeypatch):
        """Acceptance gate: 1000-event node add/remove churn at 20k
        nodes stays delta-class except genuine structural events —
        session_rebuilds from churn <= 2."""
        monkeypatch.setenv("KTPU_SESSION_DELTAS", "1")
        monkeypatch.setenv("KTPU_NODE_HEADROOM", "0.25")
        n_nodes = 20_000
        cache, be = _mk_backend(n_nodes, mesh=sim_mesh)
        decisions = [n for _, n in be.schedule_many(_pods("warm", 4))]
        assert all(d is not None for d in decisions)
        sess = be._session
        r0 = _rebuilds({"node-add", "node-remove"})
        rng = random.Random(13)
        removed = []
        for ev in range(1000):
            if removed and (ev % 2 == 1):
                cache.add_node(_node(removed.pop(rng.randrange(len(removed)))))
            else:
                i = rng.randrange(4, n_nodes)
                if f"node-{i}" in be.enc.node_index and i not in removed:
                    cache.remove_node(f"node-{i}")
                    removed.append(i)
        churn = _rebuilds({"node-add", "node-remove"}) - r0
        assert churn <= 2, f"rebuild storm: {churn} rebuilds in 1000 events"
        assert be._session is sess or churn > 0
        tail = [n for _, n in be.schedule_many(_pods("tail", 2))]
        assert all(d is not None for d in tail)


# --------------------------------------------------------- observability


class TestMeshObservability:
    def test_mesh_shards_gauge_and_labels(self, sim_mesh):
        _, be = _mk_backend(6, mesh=sim_mesh)
        assert metrics.mesh_shards.value() == 8.0
        be.schedule_many(_pods("warm", 2))
        keys = [k for k, val in metrics.session_builds.items() if val]
        assert any(k[-1] == "8" for k in keys), keys

    def test_no_mesh_blank_shards_label(self):
        _, be = _mk_backend(4, mesh=None)
        be.schedule_many(_pods("warm", 2))
        keys = [k for k, val in metrics.session_builds.items() if val]
        assert any(k[-1] == "" for k in keys), keys
