"""ShardedPallasSession (two-phase mesh scan) decision parity with the
single-device PallasSession, on a virtual 8-device CPU mesh.

The invariant: sharding the node axis must not change ONE decision —
the global normalize min/max, the PTS min-match, zone presence, and the
first-max argmax all reduce across shards exactly (VERDICT r4 #2;
reference helper/normalize_score.go:24 is the global normalize a naive
shard-local kernel would silently break).
"""

import copy

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.ops.pallas_scan import PallasSession
from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

from .test_hoisted import _encode_all, _presized_encoding
from .util import make_pod


def _templates_of(arrays):
    out, seen = [], set()
    for a in arrays:
        fp = template_fingerprint(a)
        if fp not in seen:
            seen.add(fp)
            out.append(a)
    return out


def _mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.asarray(devs[:n]), ("nodes",))


def _run_pair(nodes, init_pods, pending, batch, n_shards=8):
    """(single-device pallas decisions, sharded decisions)."""
    enc, pe = _presized_encoding(
        copy.deepcopy(nodes), copy.deepcopy(init_pods),
        copy.deepcopy(pending))
    arrays = _encode_all(enc, pe, pending)
    psess = PallasSession(enc.device_state(), _templates_of(arrays),
                          interpret=True)
    ref = []
    for i in range(0, len(pending), batch):
        b = arrays[i:i + batch]
        ref.extend(PallasSession.decisions(psess.schedule(b))[:len(b)])

    enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
    arrays2 = _encode_all(enc2, pe2, pending)
    ssess = ShardedPallasSession(
        enc2.device_state(), _templates_of(arrays2), mesh=_mesh(n_shards))
    got = []
    for i in range(0, len(pending), batch):
        b = arrays2[i:i + batch]
        got.extend(ShardedPallasSession.decisions(ssess.schedule(b))[:len(b)])
    return ref, got


class TestShardedParity:
    def test_spread_multi_batch(self):
        nodes, init_pods = synth_cluster(16, pods_per_node=2)
        pending = synth_pending_pods(36, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=12)
        assert got == ref
        assert all(d >= 0 for d in got)

    def test_no_constraints(self):
        nodes, init_pods = synth_cluster(10, pods_per_node=1)
        pending = synth_pending_pods(16, spread=False)
        ref, got = _run_pair(nodes, init_pods, pending, batch=8)
        assert got == ref

    def test_capacity_exhaustion(self):
        nodes, init_pods = synth_cluster(3, pods_per_node=0)
        for node in nodes:
            node.status.allocatable["cpu"] = "350m"
            node.status.capacity["cpu"] = "350m"
        pending = synth_pending_pods(15, spread=True)
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref
        assert -1 in got

    def test_hostname_hard_spread(self):
        nodes, init_pods = synth_cluster(6, pods_per_node=1)
        pending = []
        for i in range(10):
            pending.append(make_pod(
                f"hard-{i}", cpu="50m", labels={"app": "hard"},
                constraints=[v1.TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=v1.LABEL_HOSTNAME,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "hard"}),
                )]))
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref

    def test_odd_shard_counts(self):
        # node counts that do NOT divide the shard count: padding rows
        # must stay infeasible on every shard
        for n_nodes, shards in ((7, 4), (17, 8), (5, 2)):
            nodes, init_pods = synth_cluster(n_nodes, pods_per_node=1)
            pending = synth_pending_pods(12, spread=True)
            ref, got = _run_pair(nodes, init_pods, pending,
                                 batch=6, n_shards=shards)
            assert got == ref, (n_nodes, shards)

    def test_term_templates_parity(self):
        """Required hostname anti-affinity: the D1-D5 ucnt/kcnt carries
        shard per node; decisions must stay bit-identical (one pod per
        node, so every assume changes later pods' masks)."""
        nodes, init_pods = synth_cluster(12, pods_per_node=1)
        pending = [
            make_pod(
                f"aff-{i}", cpu="50m", labels={"app": "aff"},
                affinity=v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=[
                        v1.PodAffinityTerm(
                            label_selector=v1.LabelSelector(
                                match_labels={"app": "aff"}),
                            topology_key=v1.LABEL_HOSTNAME,
                        )])))
            for i in range(10)
        ]
        ref, got = _run_pair(nodes, init_pods, pending, batch=5)
        assert got == ref
        placed = [d for d in got if d >= 0]
        assert len(placed) == len(set(placed)) == 10  # one per node

    def test_preferred_affinity_parity(self):
        """Preferred zone affinity (D4/D5 score terms + presence flags
        ride w45/gpres with the pmax'd rowany)."""
        nodes, init_pods = synth_cluster(9, pods_per_node=1)
        pending = [
            make_pod(
                f"pref-{i}", cpu="50m", labels={"app": "pref"},
                affinity=v1.Affinity(pod_affinity=v1.PodAffinity(
                    preferred_during_scheduling_ignored_during_execution=[
                        v1.WeightedPodAffinityTerm(
                            weight=10,
                            pod_affinity_term=v1.PodAffinityTerm(
                                label_selector=v1.LabelSelector(
                                    match_labels={"app": "pref"}),
                                topology_key=v1.LABEL_ZONE,
                            ))])))
            for i in range(8)
        ]
        ref, got = _run_pair(nodes, init_pods, pending, batch=4)
        assert got == ref

    def test_parity_vs_hoisted_session_too(self):
        # transitively pinned already (pallas == hoisted), but one direct
        # check keeps the chain visible
        nodes, init_pods = synth_cluster(12, pods_per_node=2)
        pending = synth_pending_pods(18, spread=True)
        enc, pe = _presized_encoding(
            copy.deepcopy(nodes), copy.deepcopy(init_pods),
            copy.deepcopy(pending))
        arrays = _encode_all(enc, pe, pending)
        jsess = HoistedSession(enc.device_state(), _templates_of(arrays))
        ref = HoistedSession.decisions(jsess.schedule(arrays))[:len(arrays)]
        enc2, pe2 = _presized_encoding(nodes, init_pods, pending)
        arrays2 = _encode_all(enc2, pe2, pending)
        ssess = ShardedPallasSession(
            enc2.device_state(), _templates_of(arrays2), mesh=_mesh(8))
        got = ShardedPallasSession.decisions(
            ssess.schedule(arrays2))[:len(arrays2)]
        assert got == ref
