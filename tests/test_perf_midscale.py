"""Mid-scale perf gate: catches host-loop throughput regressions
in-repo instead of at the next driver bench run (VERDICT r2 weak #8 —
CI never exercised scale).

Runs the FULL loop (APIServer + informers + queue + cache + Scheduler +
TPU backend) at 500 nodes / 1000 measured pods and asserts the density
floor. Needs the real TPU chip, so it runs in a SUBPROCESS without the
suite's forced-CPU conftest env; skipped unless KTPU_MIDSCALE=1 (the
default suite stays CPU-only and fast).

    KTPU_MIDSCALE=1 python -m pytest tests/test_perf_midscale.py -q

Threshold: the reference fails density at <30 pods/s and warns at
<100 pods/s (scheduler_test.go:41,40) at 100 nodes; this build's floor
at 500 nodes through the full loop is set 4x above the warning line —
far below the ~1000 pods/s it actually does, high enough that a
host-loop regression to r2's per-pod costs (~400 pods/s) fails.
"""

import json
import os
import subprocess
import sys

import pytest

FLOOR_PODS_PER_SEC = 400.0

_SCRIPT = r"""
import json, os, sys
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, {repo!r})
from kubernetes_tpu.utils.compilation_cache import enable_persistent_cache
enable_persistent_cache()
from kubernetes_tpu.perf.harness import PodTemplate, Workload, run_workload
w = Workload(
    "midscale-gate", num_nodes=500, num_init_pods=1000, num_pods=1000,
    init_template=PodTemplate(spread_zone=True),
    template=PodTemplate(spread_zone=True), max_batch=1024, timeout=300.0,
)
r = run_workload(w)
print("MIDSCALE_RESULT " + json.dumps(r.to_dict()))
"""


@pytest.mark.skipif(
    os.environ.get("KTPU_MIDSCALE") != "1",
    reason="mid-scale perf gate needs the real TPU chip; set KTPU_MIDSCALE=1",
)
def test_full_loop_midscale_floor():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT.format(repo=repo)],
        capture_output=True, text=True, timeout=900, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(
        (ln for ln in proc.stdout.splitlines()
         if ln.startswith("MIDSCALE_RESULT ")),
        None,
    )
    assert line, f"no result line in: {proc.stdout[-500:]}"
    result = json.loads(line[len("MIDSCALE_RESULT "):])
    assert result["num_bound"] == 1000, result
    assert result["throughput_avg"] >= FLOOR_PODS_PER_SEC, (
        f"full-loop throughput regressed: {result['throughput_avg']} < "
        f"{FLOOR_PODS_PER_SEC} pods/s at 500 nodes"
    )
