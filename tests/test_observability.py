"""Metrics registry, events, leader election, trace tests.

Reference models: component-base/metrics tests, client-go record/
leaderelection tests (leaderelection_test.go — acquire, renew, lose on
expiry, second elector takes over)."""

from __future__ import annotations

import time

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset
from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.utils.metrics import Counter, Gauge, Histogram, Registry
from kubernetes_tpu.utils.trace import Trace


def test_metrics_collect_and_expose():
    reg = Registry()
    c = reg.register(Counter("requests_total", "Total requests.", ("code",)))
    c.inc(code="200")
    c.inc(code="200")
    c.inc(code="500")
    g = reg.register(Gauge("pending", "Pending items.", ("queue",)))
    g.set(7, queue="active")
    h = reg.register(Histogram("latency_seconds", "Latency.", ()))
    for val in (0.004, 0.02, 0.02, 3.0):
        h.observe(val)
    text = reg.expose()
    assert 'requests_total{code="200"} 2.0' in text
    assert 'pending{queue="active"} 7' in text
    assert "latency_seconds_count 4" in text
    assert h.percentile(50) <= 0.05
    assert h.percentile(99) >= 2.5


def test_event_recorder_aggregates():
    api = APIServer()
    cs = Clientset(api)
    rec = EventRecorder(cs, "test-component")
    pod = v1.Pod(metadata=v1.ObjectMeta(name="p", namespace="default"))
    rec.event(pod, "Normal", "Scheduled", "assigned default/p to n1")
    rec.event(pod, "Normal", "Scheduled", "assigned default/p to n1")
    assert rec.flush()  # recording is async (broadcaster semantics)
    events, _ = cs.resource("events").list()
    assert len(events) == 1
    assert events[0].count == 2
    rec.event(pod, "Warning", "FailedScheduling", "0/3 nodes")
    assert rec.flush()
    events, _ = cs.resource("events").list()
    assert len(events) == 2


def test_leader_election_failover():
    api = APIServer()
    cs = Clientset(api)
    log = []
    fast = LeaderElectionConfig(
        identity="a", lease_duration=1.0, renew_deadline=0.6, retry_period=0.2
    )
    ea = LeaderElector(
        cs, fast, lambda: log.append("a-start"), lambda: log.append("a-stop")
    )
    ea.start()
    assert ea.is_leader.wait(5)
    assert ea.leader_identity == "a"
    cfg_b = LeaderElectionConfig(
        identity="b", lease_duration=1.0, renew_deadline=0.6, retry_period=0.2
    )
    eb = LeaderElector(
        cs, cfg_b, lambda: log.append("b-start"), lambda: log.append("b-stop")
    )
    eb.start()
    time.sleep(1.0)
    assert not eb.is_leader.is_set(), "b must not steal a live lease"
    ea.stop()  # a stops renewing; lease expires; b adopts
    assert eb.is_leader.wait(10), "b must take over after expiry"
    assert eb.leader_identity == "b"
    eb.stop()
    assert "a-start" in log and "b-start" in log


def test_trace_threshold():
    tr = Trace("cycle", pod="default/p")
    tr.step("filter")
    assert not tr.log_if_long(10.0)
    import io

    buf = io.StringIO()
    time.sleep(0.02)
    tr.step("score")
    assert tr.log_if_long(0.01, out=buf)
    assert "cycle" in buf.getvalue() and "score" in buf.getvalue()
