"""Metrics registry, events, leader election, trace + flight-recorder
tests.

Reference models: component-base/metrics tests, client-go record/
leaderelection tests (leaderelection_test.go — acquire, renew, lose on
expiry, second elector takes over); the flight-recorder half covers
utils/tracing.py (ring wrap-around under concurrent writers, chrome
export, stage stats), the backend-health k8s Events, the /configz
KTPU_* knob surface, and the perf harness's per-stage latency fields."""

from __future__ import annotations

import json
import threading
import time

import pytest

from kubernetes_tpu.api import types as v1
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Clientset
from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.utils import configz, tracing
from kubernetes_tpu.utils.metrics import Counter, Gauge, Histogram, Registry
from kubernetes_tpu.utils.trace import Trace


def test_metrics_collect_and_expose():
    reg = Registry()
    c = reg.register(Counter("requests_total", "Total requests.", ("code",)))
    c.inc(code="200")
    c.inc(code="200")
    c.inc(code="500")
    g = reg.register(Gauge("pending", "Pending items.", ("queue",)))
    g.set(7, queue="active")
    h = reg.register(Histogram("latency_seconds", "Latency.", ()))
    for val in (0.004, 0.02, 0.02, 3.0):
        h.observe(val)
    text = reg.expose()
    assert 'requests_total{code="200"} 2.0' in text
    assert 'pending{queue="active"} 7' in text
    assert "latency_seconds_count 4" in text
    assert h.percentile(50) <= 0.05
    assert h.percentile(99) >= 2.5


def test_event_recorder_aggregates():
    api = APIServer()
    cs = Clientset(api)
    rec = EventRecorder(cs, "test-component")
    pod = v1.Pod(metadata=v1.ObjectMeta(name="p", namespace="default"))
    rec.event(pod, "Normal", "Scheduled", "assigned default/p to n1")
    rec.event(pod, "Normal", "Scheduled", "assigned default/p to n1")
    assert rec.flush()  # recording is async (broadcaster semantics)
    events, _ = cs.resource("events").list()
    assert len(events) == 1
    assert events[0].count == 2
    rec.event(pod, "Warning", "FailedScheduling", "0/3 nodes")
    assert rec.flush()
    events, _ = cs.resource("events").list()
    assert len(events) == 2


def test_leader_election_failover():
    api = APIServer()
    cs = Clientset(api)
    log = []
    fast = LeaderElectionConfig(
        identity="a", lease_duration=1.0, renew_deadline=0.6, retry_period=0.2
    )
    ea = LeaderElector(
        cs, fast, lambda: log.append("a-start"), lambda: log.append("a-stop")
    )
    ea.start()
    assert ea.is_leader.wait(5)
    assert ea.leader_identity == "a"
    cfg_b = LeaderElectionConfig(
        identity="b", lease_duration=1.0, renew_deadline=0.6, retry_period=0.2
    )
    eb = LeaderElector(
        cs, cfg_b, lambda: log.append("b-start"), lambda: log.append("b-stop")
    )
    eb.start()
    time.sleep(1.0)
    assert not eb.is_leader.is_set(), "b must not steal a live lease"
    ea.stop()  # a stops renewing; lease expires; b adopts
    assert eb.is_leader.wait(10), "b must take over after expiry"
    assert eb.leader_identity == "b"
    eb.stop()
    assert "a-start" in log and "b-start" in log


def test_trace_threshold():
    tr = Trace("cycle", pod="default/p")
    tr.step("filter")
    assert not tr.log_if_long(10.0)
    import io

    buf = io.StringIO()
    time.sleep(0.02)
    tr.step("score")
    assert tr.log_if_long(0.01, out=buf)
    assert "cycle" in buf.getvalue() and "score" in buf.getvalue()


# -- flight recorder (utils/tracing.py) ------------------------------------


@pytest.fixture
def recorder():
    """A private recorder at level 1 (stage spans); the global RECORDER
    is restored untouched."""
    return tracing.FlightRecorder(capacity=64, level=tracing.TRACE_STAGES)


@pytest.fixture
def traced():
    """Enable the GLOBAL recorder for a test, restore + clear after."""
    old = tracing.set_level(tracing.TRACE_PODS)
    tracing.RECORDER.clear()
    yield tracing.RECORDER
    tracing.set_level(old)
    tracing.RECORDER.clear()


class TestFlightRecorder:
    def test_ring_wraparound_under_concurrent_writers(self, recorder):
        """4 writers x 200 events into a 64-slot ring: after the join
        the ring holds 64 unique, ordered, well-formed records from the
        newest window (the monotonic slot guard keeps lagging writers
        from clobbering newer records; only a pathological deschedule
        exactly between its check and store could leave a slot one
        revolution stale, so the window assertion allows a single
        straggler) — lock-light writes may race, torn state may not."""
        n_threads, per = 4, 200

        def write(t):
            for i in range(per):
                recorder.record(f"w{t}-{i}", "dispatch", 0.0, 0.001,
                                {"t": t})

        threads = [threading.Thread(target=write, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = recorder.snapshot()
        total = n_threads * per
        assert len(events) == 64
        seqs = [e[0] for e in events]
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == 64
        newest = set(range(total - 64, total))
        assert seqs[-1] >= total - 2  # even the max slot may race once
        assert len(newest.intersection(seqs)) >= 63
        assert min(seqs) >= total - 2 * 64
        for e in events:
            assert e[2] == "dispatch" and e[6]["t"] in range(n_threads)

    def test_span_context_manager_and_stage_stats(self, recorder):
        with recorder.span("b0", "dispatch", n=4):
            time.sleep(0.005)
        with recorder.span("b0", "harvest") as sp:
            sp.set(bucket=8)
        recorder.event("device-fault", "fault", kind="timeout")
        events = recorder.snapshot()
        assert len(events) == 3
        stats = tracing.stage_stats(events)
        assert stats["dispatch"]["count"] == 1
        assert stats["dispatch"]["p50_s"] >= 0.005
        assert stats["fault"]["total_s"] == 0.0
        assert tracing.window_span(events) > 0.0
        # attrs set mid-span survive into the record
        harvest = [e for e in events if e[2] == "harvest"][0]
        assert harvest[6]["bucket"] == 8

    def test_chrome_trace_export_shape(self, recorder):
        with recorder.span("batch", "dispatch", n=2):
            pass
        chrome = tracing.chrome_trace(recorder.snapshot())
        assert len(chrome) == 1
        ev = chrome[0]
        assert ev["ph"] == "X" and ev["cat"] == "dispatch"
        assert ev["dur"] > 0 and ev["args"]["n"] == 2
        json.dumps(chrome)  # must be JSON-serializable as-is

    def test_disabled_level_is_noop_singleton(self):
        rec = tracing.FlightRecorder(capacity=16, level=0)
        assert rec.span("a", "dispatch") is tracing.NOOP_SPAN
        assert rec.span("b", "harvest", n=1) is tracing.NOOP_SPAN
        rec.record("a", "dispatch", 0.0, 1.0)
        rec.provenance("default/p", rung="pallas")
        assert rec.snapshot() == []
        assert rec.dump("device-fault-timeout") == []
        assert rec.dump_history == []

    def test_dump_writes_file_and_history(self, recorder, tmp_path):
        with recorder.span("batch", "dispatch", n=2):
            pass
        path = str(tmp_path / "dump.json")
        events = recorder.dump("device-fault-timeout", path=path,
                               kind="timeout", rung="hoisted")
        assert len(events) == 1
        assert recorder.dump_history[-1]["reason"] == "device-fault-timeout"
        assert recorder.dump_history[-1]["attrs"]["rung"] == "hoisted"
        with open(path) as f:
            rec = json.load(f)
        assert rec["events"][0]["stage"] == "dispatch"
        # the dump file renders through scripts/trace_report.py (the
        # drill's integrity check)
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "scripts"))
        import trace_report

        assert trace_report.render(path) == 0
        assert (tmp_path / "dump.chrome.json").exists()

    def test_provenance_only_at_level_2(self):
        rec = tracing.FlightRecorder(capacity=16, level=1)
        rec.provenance("default/p", rung="pallas")
        assert rec.snapshot() == []
        rec.level = 2
        rec.provenance("default/p", rung="pallas", planner="device")
        mix = tracing.provenance_mix(rec.snapshot())
        assert mix["rung"] == {"pallas": 1}
        assert mix["planner"] == {"device": 1}

    def test_threshold_trace_mirrors_into_recorder(self, traced):
        tr = Trace("cycle", pod="default/p")
        tr.step("filter")
        tr.step("score")
        tr.record_spans()
        names = [e[1] for e in traced.snapshot()]
        assert "cycle/filter" in names and "cycle/score" in names


# -- backend health -> k8s Events + /configz knobs -------------------------


def _mini_scheduler():
    from kubernetes_tpu.client import SharedInformerFactory
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from tests.util import make_node

    api = APIServer()
    cs = Clientset(api)
    cs.nodes.create(make_node("node-0"))
    factory = SharedInformerFactory(cs)
    sched = Scheduler(cs, factory, backend="tpu", pipeline_depth=2)
    factory.start()
    assert factory.wait_for_cache_sync()
    return cs, factory, sched


def test_backend_health_transitions_emit_events():
    """Ladder demotion, speculation-miss re-drives and worker restarts
    surface as k8s Events on the scheduler pseudo-object — with repeats
    AGGREGATED (one Event, bumped count), so cluster-level observers see
    device health without scraping metrics."""
    cs, factory, sched = _mini_scheduler()
    try:
        tpu = sched.tpu
        tpu.ladder.threshold = 1  # demote on the first fault
        with tpu._lock:
            tpu._device_fault_locked("raise")
        # speculation misses: two identical re-drive notices aggregate
        class _H:  # minimal speculative handle stand-ins
            speculative = True

        tpu._miss_speculative([_H()])
        tpu._miss_speculative([_H()])
        assert sched.recorder.flush(timeout=10)
        events, _ = cs.resource("events").list()
        by_reason = {}
        for e in events:
            if e.involved_object.kind == "Scheduler":
                by_reason[e.reason] = by_reason.get(e.reason, 0) + e.count
        assert by_reason.get("BackendDemoted", 0) >= 1
        assert by_reason.get("SpeculationMissRedrive", 0) == 2
        demoted = [e for e in events if e.reason == "BackendDemoted"]
        assert demoted[0].type == "Warning"
        miss = [e for e in events if e.reason == "SpeculationMissRedrive"]
        assert len(miss) == 1 and miss[0].count == 2, "repeats must aggregate"
    finally:
        sched.shutdown()
        factory.stop()


def test_configz_registers_runtime_ktpu_knobs():
    """The runtime-effective KTPU_* surface is inspectable via /configz:
    the values the backend actually RESOLVED (platform defaults applied),
    not the raw env strings."""
    cs, factory, sched = _mini_scheduler()
    try:
        snap = configz.snapshot()
        assert "ktpu" in snap
        knobs = snap["ktpu"]
        for key in ("multipod_k", "speculation", "whatif", "session_deltas",
                    "trace_level", "watchdog_timeout", "drain_timeout",
                    "pipeline_depth", "demote_threshold"):
            assert key in knobs, key
        assert knobs["multipod_k"] >= 1
        assert isinstance(knobs["speculation"], bool)
        # the /configz body serializes (the handler contract)
        json.loads(configz.handler_body())
    finally:
        sched.shutdown()
        factory.stop()


# -- harness: per-stage latency attribution --------------------------------


def test_harness_stage_latency_attribution_and_reconciliation(traced):
    """With KTPU_TRACE on, a full-loop harness run reports per-stage
    p50/p99 fields that reconcile with the measured window; with it off,
    the fields are absent (None) and the recorder stays empty."""
    from kubernetes_tpu.perf import Workload, run_workload

    w = Workload("trace-ci", num_nodes=10, num_pods=30, timeout=120,
                 max_batch=16)
    r = run_workload(w)
    assert r.trace_level == tracing.TRACE_PODS
    assert r.stage_latency, "no stage breakdown with tracing enabled"
    stages = set(r.stage_latency)
    assert {"pop", "encode", "dispatch", "harvest", "assume",
            "bind"} <= stages
    for stats in r.stage_latency.values():
        assert stats["count"] >= 1
        assert stats["p50_s"] <= stats["p99_s"]
        assert stats["total_s"] <= max(r.duration_s, 1.0) * 8
    # reconciliation: the spans cover a window consistent with the
    # measured run (pipeline stages overlap across threads, so each
    # stage's total is bounded by the span-covered wall clock, and the
    # covered window cannot exceed the measured phase by more than the
    # post-pause drain slack)
    assert r.stage_window_s > 0
    assert r.stage_window_s <= r.duration_s + 35.0
    dispatch_total = r.stage_latency["dispatch"]["total_s"]
    assert dispatch_total <= r.stage_window_s + 1.0
    # per-pod provenance recorded one record per decided pod
    prov = r.stage_latency.get("provenance")
    assert prov is not None and prov["count"] >= r.num_bound
    # rows survive JSON round-trips for the bench artifacts
    json.dumps(r.to_dict())

    tracing.set_level(0)
    tracing.RECORDER.clear()
    r2 = run_workload(w)
    assert r2.trace_level == 0 and r2.stage_latency is None
    assert tracing.RECORDER.snapshot() == []
