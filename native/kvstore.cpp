// Revisioned, ordered, watchable KV store — native (C++) etcd-equivalent.
//
// Reference: the reference cluster keeps all state in etcd, a separate
// native process reached over gRPC (staging/src/k8s.io/apiserver/pkg/
// storage/etcd3/store.go:143 Create, :286 GuaranteedUpdate, :816 Watch;
// SURVEY.md §2.4.2). This library reproduces the same transactional
// semantics as kubernetes_tpu/store/kv.py behind a C ABI consumed via
// ctypes (kubernetes_tpu/store/native.py):
//
//   * one monotonically-increasing int64 revision across all keys;
//   * create-if-absent; update/delete guarded by expected mod revision;
//   * prefix range reads returning (items, store revision);
//   * watches replayed from any uncompacted revision, then live, with a
//     bounded event log (compaction -> -2 "compacted", the 410 Gone
//     analog).
//
// All blocking waits happen in native code (std::condition_variable), so
// Python watch polls release the GIL — informer fan-out does not serialize
// the interpreter the way the pure-Python store's queue.get does.
//
// Wire format (list/event buffers) is length-prefixed little-endian; the
// Python side slices it with struct.unpack_from. Buffers are malloc'd here
// and released with kv_buf_free.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Value {
  std::string data;
  int64_t create_rev = 0;
  int64_t mod_rev = 0;
};

struct EventRec {
  uint8_t type;  // 0 ADDED, 1 MODIFIED, 2 DELETED
  std::string key;
  std::string value;  // current (ADDED/MODIFIED) or last (DELETED)
  int64_t rev;
};

struct WatchState {
  std::string prefix;
  std::deque<EventRec> queue;
  bool stopped = false;
};

struct Store {
  std::mutex mu;
  std::condition_variable cv;  // signaled on any new event
  std::map<std::string, Value> data;  // ordered -> prefix scans
  std::deque<EventRec> history;
  size_t history_limit;
  int64_t rev = 0;
  int64_t compacted_rev = 0;
  std::unordered_map<int64_t, std::shared_ptr<WatchState>> watches;
  int64_t next_watch_id = 1;

  explicit Store(size_t limit) : history_limit(limit) {}

  void append_event(uint8_t type, const std::string& key,
                    const std::string& value) {
    EventRec ev{type, key, value, rev};
    history.push_back(ev);
    while (history.size() > history_limit) {
      compacted_rev = history.front().rev;
      history.pop_front();
    }
    for (auto& [id, w] : watches) {
      if (!w->stopped && key.compare(0, w->prefix.size(), w->prefix) == 0) {
        w->queue.push_back(ev);
      }
    }
    cv.notify_all();
  }
};

char* alloc_buf(size_t n) { return static_cast<char*>(malloc(n)); }

void put_u32(std::string& out, uint32_t v) {
  out.append(reinterpret_cast<const char*>(&v), 4);
}
void put_i64(std::string& out, int64_t v) {
  out.append(reinterpret_cast<const char*>(&v), 8);
}

char* to_heap(const std::string& s, int64_t* out_len) {
  char* buf = alloc_buf(s.size());
  memcpy(buf, s.data(), s.size());
  *out_len = static_cast<int64_t>(s.size());
  return buf;
}

void encode_event(std::string& out, const EventRec& ev) {
  out.push_back(static_cast<char>(ev.type));
  put_u32(out, static_cast<uint32_t>(ev.key.size()));
  out.append(ev.key);
  put_u32(out, static_cast<uint32_t>(ev.value.size()));
  out.append(ev.value);
  put_i64(out, ev.rev);
}

}  // namespace

extern "C" {

void* kv_new(int64_t history_limit) {
  return new Store(history_limit > 0 ? static_cast<size_t>(history_limit)
                                     : 100000);
}

void kv_free(void* h) { delete static_cast<Store*>(h); }

void kv_buf_free(char* p) { free(p); }

// -> new revision, or -1 if the key exists
int64_t kv_create(void* h, const char* key, const char* val, int64_t len) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  std::string k(key);
  if (s->data.count(k)) return -1;
  s->rev += 1;
  Value v{std::string(val, static_cast<size_t>(len)), s->rev, s->rev};
  s->data.emplace(k, v);
  s->append_event(0, k, v.data);
  return s->rev;
}

// expected_rev: -1 = unconditional. -> new revision, -1 not found,
// -2 conflict
int64_t kv_update(void* h, const char* key, const char* val, int64_t len,
                  int64_t expected_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->data.find(key);
  if (it == s->data.end()) return -1;
  if (expected_rev >= 0 && it->second.mod_rev != expected_rev) return -2;
  s->rev += 1;
  it->second.data.assign(val, static_cast<size_t>(len));
  it->second.mod_rev = s->rev;
  s->append_event(1, it->first, it->second.data);
  return s->rev;
}

// -> revision of the delete, -1 not found, -2 conflict
int64_t kv_delete(void* h, const char* key, int64_t expected_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->data.find(key);
  if (it == s->data.end()) return -1;
  if (expected_rev >= 0 && it->second.mod_rev != expected_rev) return -2;
  s->rev += 1;
  std::string last = std::move(it->second.data);
  std::string k = it->first;
  s->data.erase(it);
  s->append_event(2, k, last);
  return s->rev;
}

// -> malloc'd value buffer (caller frees), or NULL if absent.
char* kv_get(void* h, const char* key, int64_t* out_len,
             int64_t* out_create_rev, int64_t* out_mod_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->data.find(key);
  if (it == s->data.end()) return nullptr;
  *out_create_rev = it->second.create_rev;
  *out_mod_rev = it->second.mod_rev;
  return to_heap(it->second.data, out_len);
}

// Buffer: [u32 n] n*{u32 klen, key, u32 vlen, val, i64 create, i64 mod}
// [i64 store_rev]; caller frees.
char* kv_list(void* h, const char* prefix, int64_t* out_len) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  std::string p(prefix);
  std::string out;
  uint32_t n = 0;
  std::string body;
  for (auto it = s->data.lower_bound(p); it != s->data.end(); ++it) {
    if (it->first.compare(0, p.size(), p) != 0) break;
    put_u32(body, static_cast<uint32_t>(it->first.size()));
    body.append(it->first);
    put_u32(body, static_cast<uint32_t>(it->second.data.size()));
    body.append(it->second.data);
    put_i64(body, it->second.create_rev);
    put_i64(body, it->second.mod_rev);
    n += 1;
  }
  put_u32(out, n);
  out.append(body);
  put_i64(out, s->rev);
  return to_heap(out, out_len);
}

int64_t kv_rev(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->rev;
}

int64_t kv_compacted_rev(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->compacted_rev;
}

// Drop history up to and including `revision` (etcd compaction).
void kv_compact(void* h, int64_t revision) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  while (!s->history.empty() && s->history.front().rev <= revision) {
    s->compacted_rev = s->history.front().rev;
    s->history.pop_front();
  }
}

// since_rev: -1 = live-only ("from now"); >= 0 replays history with
// rev > since_rev. -> watch id, or -2 if since_rev predates the retained
// log (compacted, the 410 Gone analog).
int64_t kv_watch_new(void* h, const char* prefix, int64_t since_rev) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto w = std::make_shared<WatchState>();
  w->prefix = prefix;
  if (since_rev >= 0) {
    if (since_rev < s->compacted_rev) return -2;
    for (const auto& ev : s->history) {
      if (ev.rev > since_rev &&
          ev.key.compare(0, w->prefix.size(), w->prefix) == 0) {
        w->queue.push_back(ev);
      }
    }
  }
  int64_t id = s->next_watch_id++;
  s->watches.emplace(id, std::move(w));
  return id;
}

void kv_watch_free(void* h, int64_t wid) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->watches.find(wid);
  if (it != s->watches.end()) {
    it->second->stopped = true;
    s->watches.erase(it);
  }
  s->cv.notify_all();
}

// Poll one event. Returns malloc'd event buffer (see encode_event) or
// NULL on timeout / unknown watch. Blocks in native code (GIL released
// by ctypes).
char* kv_watch_poll(void* h, int64_t wid, int64_t timeout_ms,
                    int64_t* out_len) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  auto it = s->watches.find(wid);
  if (it == s->watches.end()) return nullptr;
  std::shared_ptr<WatchState> w = it->second;
  if (w->queue.empty() && timeout_ms > 0) {
    s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
      return w->stopped || !w->queue.empty() ||
             s->watches.find(wid) == s->watches.end();
    });
  }
  if (s->watches.find(wid) == s->watches.end() || w->queue.empty()) {
    return nullptr;
  }
  EventRec ev = std::move(w->queue.front());
  w->queue.pop_front();
  std::string out;
  encode_event(out, ev);
  return to_heap(out, out_len);
}

}  // extern "C"
