/* pause: the pod-sandbox holder process.
 *
 * Reference: build/pause/linux/pause.c — the single compiled-C component
 * in the reference tree. It is the first process of every pod sandbox:
 * it holds the pod's shared namespaces open and, as PID 1 of the pod,
 * reaps orphaned zombies (sigreap), exiting on SIGINT/SIGTERM.
 * Faithful equivalent for the TPU build's runtime (SURVEY.md §2.4.1).
 */

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#define STRINGIFY(x) #x
#define VERSION_STRING(x) STRINGIFY(x)

#ifndef VERSION
#define VERSION HEAD
#endif

static void sigdown(int signo) {
  psignal(signo, "Shutting down, got signal");
  exit(0);
}

static void sigreap(int signo) {
  (void)signo;
  while (waitpid(-1, NULL, WNOHANG) > 0)
    ;
}

int main(int argc, char **argv) {
  int i;
  for (i = 1; i < argc; ++i) {
    if (!strcasecmp(argv[i], "-v")) {
      printf("pause.c %s\n", VERSION_STRING(VERSION));
      return 0;
    }
  }

  if (getpid() != 1)
    /* Not an error because pause sees use outside of infra containers. */
    fprintf(stderr, "Warning: pause should be the first process\n");

  if (sigaction(SIGINT, &(struct sigaction){.sa_handler = sigdown}, NULL) < 0)
    return 1;
  if (sigaction(SIGTERM, &(struct sigaction){.sa_handler = sigdown}, NULL) < 0)
    return 2;
  if (sigaction(SIGCHLD,
                &(struct sigaction){.sa_handler = sigreap,
                                    .sa_flags = SA_NOCLDSTOP},
                NULL) < 0)
    return 3;

  for (;;)
    pause();
  fprintf(stderr, "Error: infinite loop terminated\n");
  return 42;
}
