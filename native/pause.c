/* sandbox-hold: the pod-sandbox holder process (original implementation).
 *
 * Role (behavioral spec, cf. the reference's pause container described in
 * SURVEY.md §2.4.1): run as the first process of a pod sandbox, keep the
 * pod's shared kernel namespaces alive by simply existing, reap any
 * orphaned children re-parented onto it (it is PID 1 inside the sandbox),
 * and terminate promptly on SIGINT or SIGTERM.
 *
 * Design: rather than installing async signal handlers and spinning on
 * pause(), this implementation blocks the signals of interest and drives
 * everything from a synchronous sigwaitinfo() loop — no handler
 * re-entrancy to reason about, and zombie reaping happens in ordinary
 * program context.
 */

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

#ifndef SANDBOX_HOLD_VERSION
#define SANDBOX_HOLD_VERSION "dev"
#endif

/* Collect every terminated child without blocking; called whenever a
 * SIGCHLD is delivered (and once at startup, in case children exited
 * before our mask was in place). */
static void reap_children(void) {
  pid_t done;
  do {
    done = waitpid(-1, NULL, WNOHANG);
  } while (done > 0 || (done < 0 && errno == EINTR));
}

int main(int argc, char **argv) {
  sigset_t interest;
  int signo;

  if (argc > 1 && strcmp(argv[1], "--version") == 0) {
    puts("sandbox-hold " SANDBOX_HOLD_VERSION);
    return 0;
  }

  if (getpid() != 1)
    fprintf(stderr,
            "sandbox-hold: note: not PID 1; orphan reaping only covers "
            "direct children\n");

  sigemptyset(&interest);
  sigaddset(&interest, SIGINT);
  sigaddset(&interest, SIGTERM);
  sigaddset(&interest, SIGCHLD);
  if (sigprocmask(SIG_BLOCK, &interest, NULL) != 0) {
    perror("sandbox-hold: sigprocmask");
    return 1;
  }

  reap_children();

  for (;;) {
    signo = sigwaitinfo(&interest, NULL);
    if (signo < 0) {
      if (errno == EINTR)
        continue;
      perror("sandbox-hold: sigwaitinfo");
      return 1;
    }
    if (signo == SIGCHLD) {
      reap_children();
    } else {
      /* SIGINT / SIGTERM: orderly sandbox teardown. */
      return 0;
    }
  }
}
