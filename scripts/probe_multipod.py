"""Probe: multi-pod scan-step conflict rate and step cost vs k
(ISSUE 6 tooling satellite — picks the default KTPU_MULTIPOD_K per
workload class).

Builds a TPU-backend cluster directly (no apiserver — this measures the
session scan, not the loop), warms it to realistic utilization, then
runs the SAME measured batches through fresh sessions built at each k
in --ks for three workload profiles shaped like the bench matrix:

  * default   — soft zone-spread pods (Default-5000n shape): conflicts
                only through the fit/balanced/least recheck, so big k
                should hold a near-zero conflict rate until nodes fill;
  * pts       — HARD zone-spread (PTS-heavy shape): every pod of a step
                moves the zone counts every other pod reads, so the
                PTS match-gate fires and the rate approaches (k-1)/k;
  * ipachurn  — required anti-affinity by hostname (IPA-churn shape):
                the template-interference superset (G_ipa) is hot for
                the same reason.

For each (profile, k) the probe reports pods/step, the measured
conflict rate, per-pod step cost, and the implied speedup vs k=1 —
and asserts decisions stay bit-identical to the k=1 reference (the
whole point of EXACT conflict replay). CPU-runnable as-is through the
hoisted session (the in-device lax.cond replay path):

    JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python scripts/probe_multipod.py

On a TPU it additionally probes the pallas session (conflict-SUFFIX
contract: the probe replays the uncommitted suffix through the live
session exactly like tpu_backend._harvest_locked does).
"""

import argparse
import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402,F401

from kubernetes_tpu.api import types as v1  # noqa: E402
from kubernetes_tpu.ops.hoisted import HoistedSession  # noqa: E402
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache  # noqa: E402
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend  # noqa: E402
from kubernetes_tpu.testing.synth import make_node, make_pod  # noqa: E402


def spread_pod(name, hard=False):
    return make_pod(
        name, namespace="default", cpu="100m", memory="64Mi",
        labels={"app": "perf"},
        constraints=[v1.TopologySpreadConstraint(
            max_skew=1, topology_key=v1.LABEL_ZONE,
            when_unsatisfiable=(
                "DoNotSchedule" if hard else "ScheduleAnyway"),
            label_selector=v1.LabelSelector(match_labels={"app": "perf"}),
        )],
    )


def anti_pod(name):
    return make_pod(
        name, namespace="default", cpu="100m", memory="64Mi",
        labels={"app": "anti"},
        affinity=v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "anti"}),
                    topology_key=v1.LABEL_HOSTNAME,
                )
            ]
        )),
    )


PROFILES = {
    "default": lambda i: spread_pod(f"d-{i}"),
    "pts": lambda i: spread_pod(f"p-{i}", hard=True),
    "ipachurn": lambda i: anti_pod(f"a-{i}"),
}


def build_backend(n_nodes: int, reserve_pods: int):
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"node-{i}",
            labels={v1.LABEL_HOSTNAME: f"node-{i}",
                    v1.LABEL_ZONE: f"zone-{i % 3}"},
        ))
    # pre-size the pod table like the perf harness: a capacity-ladder
    # walk mid-probe would be a structural rebuild, not what we measure
    be.enc.reserve(pods=reserve_pods)
    return cache, be


def land_batch(session, arrays):
    """Run one batch to completion the way tpu_backend._harvest_locked
    does: schedule, then — for sessions on the conflict-SUFFIX contract
    (pallas/sharded; hoisted replays in-device and always returns
    suffix None) — replay the uncommitted suffix through the session
    until everything landed. Returns (decisions, n_conflicts)."""
    decisions = []
    conflicts = 0
    while arrays:
        ys = session.schedule(arrays)
        got = session.decisions(ys)
        n_conf, suffix = type(session).conflict_stats(ys)
        conflicts += n_conf
        if suffix is None:
            decisions.extend(got)
            break
        decisions.extend(got[:suffix])
        arrays = arrays[suffix:]
    return decisions, conflicts


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=200)
    ap.add_argument("--warm-pods", type=int, default=128)
    ap.add_argument("--pods", type=int, default=512,
                    help="measured pods per profile")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--ks", type=int, nargs="+", default=[1, 2, 4, 8, 16])
    args = ap.parse_args()

    platform = jax.devices()[0].platform
    print(f"platform={platform} nodes={args.nodes} "
          f"pods={args.pods} batch={args.batch} ks={args.ks}")

    for profile, mk in PROFILES.items():
        cache, be = build_backend(
            args.nodes, 2 * (args.warm_pods + args.pods) + 64)
        # warm through the backend: registers the template, fills the
        # cluster to realistic utilization, and confirms binds into the
        # encoding (so the measured sessions see occupied nodes)
        warm = [mk(f"warm-{i}") for i in range(args.warm_pods)]
        for p, node in be.schedule_many(warm):
            if node:
                p.spec.node_name = node  # landed in enc by schedule_many
        templates = list(be._known_templates.values())
        cluster = be.enc.device_state()
        weights = be.weights
        arrays = []
        for i in range(args.pods):
            enc = be.pe.encode(mk(i))
            arrays.append(
                {k: v for k, v in enc.items() if not k.startswith("_")})
        batches = [arrays[i:i + args.batch]
                   for i in range(0, len(arrays), args.batch)]

        sessions = {"hoisted": lambda k: HoistedSession(
            cluster, templates, weights, multipod_k=k)}
        if platform == "tpu":
            from kubernetes_tpu.ops.pallas_scan import PallasSession

            sessions["pallas"] = lambda k: PallasSession(
                cluster, templates, weights, multipod_k=k)

        for kind, build in sessions.items():
            print(f"\n--- {profile} / {kind} ---")
            ref = None
            base_cost = None
            for k in args.ks:
                sess = build(k)
                # warm dispatch: absorb the (k-specific) scan compile
                land_batch(build(k), batches[0])
                t0 = time.perf_counter()
                decisions = []
                conflicts = 0
                for b in batches:
                    d, c = land_batch(sess, list(b))
                    decisions.extend(d)
                    conflicts += c
                dt = time.perf_counter() - t0
                if ref is None:
                    ref = decisions
                    base_cost = dt
                ok = decisions == ref
                rate = conflicts / max(1, len(decisions))
                print(f"  k={k:3d}: {1e6 * dt / len(decisions):8.1f} "
                      f"us/pod  conflict_rate={rate:6.3f}  "
                      f"speedup_vs_k1={base_cost / dt:5.2f}x  "
                      f"parity={'OK' if ok else 'MISMATCH'}")
                if not ok:
                    print(f"!! {profile}/{kind} k={k}: decisions diverged "
                          f"from the k=1 reference", file=sys.stderr)
                    return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
