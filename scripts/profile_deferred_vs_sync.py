import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = 5000
B = 1024
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(5 * B, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)

def encode_batch(pods):
    return [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pods]

all_arrays = [encode_batch(pending[i*B:(i+1)*B]) for i in range(5)]
templates, seen = [], set()
for a in all_arrays[0]:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
sess = HoistedSession(enc.device_state(), templates)
ys = sess.schedule(all_arrays[0])
jax.block_until_ready(ys["best"])  # warm, no D2H
t_all0 = time.perf_counter()
ys_list = []
for i in (1, 2, 3, 4):
    t0 = time.perf_counter()
    y = sess.schedule(all_arrays[i])
    jax.block_until_ready(y["best"])
    ys_list.append(y)
    print(f"enqueue+block batch{i}: {1e3*(time.perf_counter()-t0):.1f}ms")
t0 = time.perf_counter()
first = np.asarray(ys_list[0]["best"])
print(f"first fetch: {1e3*(time.perf_counter()-t0):.1f}ms")
t0 = time.perf_counter()
rest = [np.asarray(y["best"]) for y in ys_list[1:]]
print(f"rest fetches: {1e3*(time.perf_counter()-t0):.1f}ms")
print(f"TOTAL 4 batches + all fetches: {1e3*(time.perf_counter()-t_all0):.1f}ms "
      f"({1e3*(time.perf_counter()-t_all0)/(4*1024):.3f} ms/pod)")
