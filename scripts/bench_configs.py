"""Run the five BASELINE.json benchmark configs through the FULL
scheduler loop (perf/harness.py: APIServer + informers + queue + cache +
Scheduler with the TPU backend) and write one JSON line per config to
BENCH_CONFIGS.json.

This is the harness-level counterpart of bench.py (which drives the
session kernel directly): the reference's scheduler_perf runs the real
scheduler against a real apiserver (test/integration/scheduler_perf/
util.go:61 mustSetupScheduler), so the headline numbers must reproduce
through the same full loop here.

Usage: python scripts/bench_configs.py [config-name ...]
(no args = the full matrix; see CONFIGS for the names)
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")
# the mesh rows (mesh20k/50k/100k) shard the node axis over 8 devices;
# on a CPU host the devices are simulated (harmless on real chips: the
# flag only multiplies the HOST platform). Must land before jax imports.
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()

from kubernetes_tpu.perf.harness import (  # noqa: E402
    PodTemplate,
    Workload,
    run_workload,
)

# The five north-star configs (BASELINE.md "Benchmark configs to
# reproduce"; shapes from the reference's performance-config.yaml)
CONFIGS = {
    # SchedulingBasic 500/1000 (CPU-baseline shape)
    "basic": Workload(
        "SchedulingBasic-500", num_nodes=500, num_init_pods=1000,
        num_pods=1000, max_batch=1024,
    ),
    # 5000 nodes / 10k pods, default profile (init pods share the
    # template so every kernel shape compiles before the measured window)
    # batch 2048 beats 4096 here since the r3 host-loop batching: same
    # device amortization, steadier bind stream (throughput_p50 > 0)
    "default5000": Workload(
        "Default-5000n-10k", num_nodes=5000, num_init_pods=6144,
        num_pods=10000, init_template=PodTemplate(spread_zone=True),
        template=PodTemplate(spread_zone=True), max_batch=2048,
        timeout=900.0,
    ),
    # PodTopologySpread-heavy: 5000 nodes, 3 zones, maxSkew=1, 20k pods
    "pts20k": Workload(
        "PTS-heavy-5000n-20k", num_nodes=5000, num_init_pods=4096,
        num_pods=20000,
        init_template=PodTemplate(spread_zone=True, spread_zone_hard=True),
        template=PodTemplate(spread_zone=True, spread_zone_hard=True),
        max_batch=2048, timeout=1200.0,
    ),
    # InterPodAffinity churn: 2000 nodes, 5000 required-anti-affinity pods
    # (hostname terms: 2000 bindable, 3000 permanently pending -> the
    # stall_stop ends the run once the scheduler has churned through them)
    "ipachurn": Workload(
        "IPA-churn-2000n-5000", num_nodes=2000, num_init_pods=1024,
        num_pods=5000,
        init_template=PodTemplate(anti_affinity_hostname=True,
                                  labels={"app": "churn"}),
        template=PodTemplate(anti_affinity_hostname=True,
                             labels={"app": "churn"}),
        max_batch=1024, timeout=900.0, stall_stop=15.0,
        saturating=True,  # ~2000 bindable of 5000 by design
    ),
    # gang stress: 1000 x 8-pod groups, 4000 GPU nodes. Batch 1024:
    # same ~1000 pods/s as 2048 but attempt_p50 3.5s -> 1.4s (the r3
    # profile's "smaller overlapped waves" — wave cadence, not CPU,
    # bounds gang latency)
    "gang": Workload(
        "Gang-4000n-1000x8", num_nodes=4000, num_init_pods=2048,
        num_pods=8000, gang_size=8,
        init_template=PodTemplate(extended={"example.com/gpu": "1"}),
        template=PodTemplate(extended={"example.com/gpu": "1"}),
        node_extended={"example.com/gpu": "8"},
        max_batch=1024, timeout=900.0,
    ),
    # rank-scaled gang rows (round 18): the same GPU cluster at 64- and
    # 256-rank gangs — the MPI-style tightly-coupled shapes the ROADMAP
    # names. A 64-rank gang spans 8 nodes, a 256-rank gang 32 nodes, so
    # these rows stress the all-or-nothing permit wave (one straggler
    # parks 63/255 siblings) rather than per-pod throughput; the
    # headline pair is aggregate pods/s + gang_admission_p99, and the
    # gang_{rollbacks,rejected} counters must read 0 on a clean run.
    # Batch >= gang_size keeps each wave inside one dispatch bucket.
    "gang64": Workload(
        "Gang-4000n-64x64", num_nodes=4000, num_init_pods=2048,
        num_pods=4096, gang_size=64,
        init_template=PodTemplate(extended={"example.com/gpu": "1"}),
        template=PodTemplate(extended={"example.com/gpu": "1"}),
        node_extended={"example.com/gpu": "8"},
        max_batch=1024, timeout=900.0,
    ),
    "gang256": Workload(
        "Gang-4000n-8x256", num_nodes=4000, num_init_pods=2048,
        num_pods=2048, gang_size=256,
        init_template=PodTemplate(extended={"example.com/gpu": "1"}),
        template=PodTemplate(extended={"example.com/gpu": "1"}),
        node_extended={"example.com/gpu": "8"},
        max_batch=1024, timeout=900.0,
    ),
    # Preemption (performance-config.yaml Preemption section shape):
    # 500 nodes saturated by 2000 low-priority pods (4 x 900m fills a
    # 4-CPU node); 500 high-priority pods must each evict a victim via
    # the DefaultPreemption dry-run, then bind on the freed node
    "preemption": Workload(
        "Preemption-500n-500hi", num_nodes=500, num_init_pods=2000,
        num_pods=500,
        init_template=PodTemplate(cpu="900m", memory="64Mi", priority=1),
        template=PodTemplate(cpu="900m", memory="64Mi", priority=100),
        max_batch=512, timeout=900.0, stall_stop=30.0,
    ),
    # Unschedulable churn (the reference's Unschedulable workload
    # variants): every 3rd measured pod requests 8 CPU (> any node) and
    # churns permanently; the schedulable majority binds through the
    # noise. stall_stop ends the run once only churners remain.
    # batch 512 (not 1024): the bind stream lands at batch-harvest
    # boundaries; at 1024 a median SECOND of the short measured window
    # saw zero binds (throughput_p50 = 0) while the avg was fine —
    # finer batches trade nothing measurable here for a steady cadence
    "unschedchurn": Workload(
        "Unschedulable-churn-500n", num_nodes=500, num_init_pods=1000,
        num_pods=3000,
        init_template=PodTemplate(spread_zone=True),
        template=PodTemplate(spread_zone=True),
        second_template=PodTemplate(cpu="8", memory="64Gi"),
        second_every=3,
        max_batch=512, timeout=900.0, stall_stop=15.0,
        saturating=True,  # 1000 of 3000 can never fit by design
    ),
    # -- the volume/affinity tail of the reference's matrix
    #    (performance-config.yaml:51-272), round-4 additions ------------
    # SchedulingSecrets: secret-volume pods (no scheduling constraint;
    # pins that volume-bearing non-PVC pods keep the kernel fast path)
    "secrets": Workload(
        "SchedulingSecrets-500n", num_nodes=500, num_init_pods=1000,
        num_pods=1000, template=PodTemplate(secret_volumes=2),
        max_batch=1024,
    ),
    # SchedulingInTreePVs: one pre-bound zonal PV+PVC per pod — VolumeZone
    # constraints ride the kernel's node-affinity mask (volume_device.py)
    "intreepvs": Workload(
        "SchedulingInTreePVs-500n", num_nodes=500, num_init_pods=1000,
        num_pods=1000,
        init_template=PodTemplate(with_pvc="zonal"),  # same shapes as
        template=PodTemplate(with_pvc="zonal"),  # measured (ref config
        max_batch=1024, timeout=900.0,  # gives init pods PVs too)
    ),
    # SchedulingCSIPVs: pre-bound CSI PVs — attach limits ride the
    # resource-fit mask via attachable-volumes-csi-* scalars
    "csipvs": Workload(
        "SchedulingCSIPVs-500n", num_nodes=500, num_init_pods=1000,
        num_pods=1000,
        init_template=PodTemplate(with_pvc="csi"),
        template=PodTemplate(with_pvc="csi"),
        max_batch=1024, timeout=900.0,
    ),
    # SchedulingPodAffinity: required zone affinity toward self-labels
    "podaffinity": Workload(
        "SchedulingPodAffinity-500n", num_nodes=500, num_init_pods=1000,
        num_pods=1000,
        init_template=PodTemplate(labels={"app": "aff"}),
        template=PodTemplate(pod_affinity_zone=True, labels={"app": "aff"}),
        max_batch=1024, timeout=900.0,
    ),
    # SchedulingPreferredPodAffinity / ...AntiAffinity: soft zone terms
    "prefaffinity": Workload(
        "SchedulingPreferredPodAffinity-500n", num_nodes=500,
        num_init_pods=1000, num_pods=1000,
        init_template=PodTemplate(labels={"app": "aff"}),
        template=PodTemplate(preferred_affinity_zone=True,
                             labels={"app": "aff"}),
        max_batch=1024, timeout=900.0,
    ),
    "prefantiaffinity": Workload(
        "SchedulingPreferredPodAntiAffinity-500n", num_nodes=500,
        num_init_pods=1000, num_pods=1000,
        init_template=PodTemplate(labels={"app": "aff"}),
        template=PodTemplate(preferred_anti_affinity_zone=True,
                             labels={"app": "aff"}),
        max_batch=1024, timeout=900.0,
    ),
    # SchedulingNodeAffinity: required node affinity zone In [0, 1]
    "nodeaffinity": Workload(
        "SchedulingNodeAffinity-500n", num_nodes=500, num_init_pods=1000,
        num_pods=1000,
        template=PodTemplate(node_affinity_zones=["zone-0", "zone-1"]),
        max_batch=1024,
    ),
    # SchedulingMigratedInTreePVs (performance-config.yaml:99-135):
    # in-tree AWS EBS PVs ride the csi-translation layer onto the same
    # kernel attach-scalar machinery as native CSI PVs
    "migratedpvs": Workload(
        "SchedulingMigratedInTreePVs-500n", num_nodes=500,
        num_init_pods=1000, num_pods=1000,
        init_template=PodTemplate(with_pvc="migrated"),
        template=PodTemplate(with_pvc="migrated"),
        max_batch=1024, timeout=900.0,
    ),
    # Preemption with PDB-covered victims: same shape as preemption but
    # every victim is under a PodDisruptionBudget — the planner's
    # vectorized filterPodsWithPDBViolation + violating-first reprieve
    # are on the measured path (VERDICT r4 #6)
    "preemptionpdb": Workload(
        "Preemption-PDB-500n-500hi", num_nodes=500, num_init_pods=2000,
        num_pods=500,
        init_template=PodTemplate(cpu="900m", memory="64Mi", priority=1,
                                  labels={"app": "victim"}),
        template=PodTemplate(cpu="900m", memory="64Mi", priority=100),
        max_batch=512, timeout=900.0, stall_stop=30.0,
        pdb_disruptions_allowed=2000,
    ),
    # Preemption with AFFINITY-carrying preemptors: the measured pods
    # carry a required pod-affinity term toward the victims' app label
    # (zone topology), putting every preemptor OUTSIDE the numpy fast
    # planner's envelope — before the device what-if planner this row
    # walked the oracle dry-run per candidate node. The per-rep
    # planner-path + what-if-launch counters adjudicate the
    # oracle-bound -> dispatch-bound claim on the chip rerun.
    "preemptionipa": Workload(
        "Preemption-IPA-500n-500hi", num_nodes=500, num_init_pods=2000,
        num_pods=500,
        init_template=PodTemplate(cpu="900m", memory="64Mi", priority=1,
                                  labels={"app": "victim"}),
        template=PodTemplate(cpu="900m", memory="64Mi", priority=100,
                             pod_affinity_zone=True,
                             labels={"app": "victim"}),
        max_batch=512, timeout=900.0, stall_stop=30.0,
    ),
    # 5000-node PV variant: the volume class at headline scale
    "intreepvs5000": Workload(
        "SchedulingInTreePVs-5000n", num_nodes=5000, num_init_pods=2048,
        num_pods=5000,
        init_template=PodTemplate(with_pvc="zonal"),
        template=PodTemplate(with_pvc="zonal"),
        max_batch=2048, timeout=900.0,
    ),
    # -- 5000-node affinity variants: the reference's matrix runs every
    #    affinity workload at BOTH 500 and 5000 nodes
    #    (performance-config.yaml:137-272); only the 500n halves were
    #    recorded through r5 ---------------------------------------------
    "podaffinity5000": Workload(
        "SchedulingPodAffinity-5000n", num_nodes=5000, num_init_pods=2048,
        num_pods=5000,
        init_template=PodTemplate(labels={"app": "aff"}),
        template=PodTemplate(pod_affinity_zone=True, labels={"app": "aff"}),
        max_batch=2048, timeout=900.0,
    ),
    "prefaffinity5000": Workload(
        "SchedulingPreferredPodAffinity-5000n", num_nodes=5000,
        num_init_pods=2048, num_pods=5000,
        init_template=PodTemplate(labels={"app": "aff"}),
        template=PodTemplate(preferred_affinity_zone=True,
                             labels={"app": "aff"}),
        max_batch=2048, timeout=900.0,
    ),
    "prefantiaffinity5000": Workload(
        "SchedulingPreferredPodAntiAffinity-5000n", num_nodes=5000,
        num_init_pods=2048, num_pods=5000,
        init_template=PodTemplate(labels={"app": "aff"}),
        template=PodTemplate(preferred_anti_affinity_zone=True,
                             labels={"app": "aff"}),
        max_batch=2048, timeout=900.0,
    ),
    "nodeaffinity5000": Workload(
        "SchedulingNodeAffinity-5000n", num_nodes=5000,
        num_init_pods=2048, num_pods=5000,
        template=PodTemplate(node_affinity_zones=["zone-0", "zone-1"]),
        max_batch=2048, timeout=900.0,
    ),
    # -- multi-host mesh scale-out (round 15): the node axis sharded
    #    over an 8-device mesh (simulated on CPU via the XLA_FLAGS set
    #    above; real ICI on a pod slice). Rows prove the 50k-100k-node
    #    regime is survivable host-side — per-host session arrays are
    #    bounded to Nps/8 rows — and that throughput holds while the
    #    encoding/cache layers carry 20x the node count of the
    #    single-device headline rows. Pod counts stay moderate: these
    #    rows measure node-axis scale, not pod backlog (the 5000n rows
    #    own that axis).
    "mesh20k": Workload(
        "Mesh-20000n-8sh", num_nodes=20000, num_init_pods=1024,
        num_pods=4096, mesh_devices=8, max_batch=1024, timeout=1800.0,
    ),
    "mesh50k": Workload(
        "Mesh-50000n-8sh", num_nodes=50000, num_init_pods=512,
        num_pods=2048, mesh_devices=8, max_batch=512, timeout=2400.0,
    ),
    "mesh100k": Workload(
        "Mesh-100000n-8sh", num_nodes=100000, num_init_pods=256,
        num_pods=1024, mesh_devices=8, max_batch=256, timeout=3600.0,
    ),
}


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2]


def main() -> None:
    """Each config runs BENCH_REPS times (VERDICT r3 weak #3: single
    runs made the recorded number whichever run got committed last);
    the row carries the MEDIAN run's full detail plus per-rep
    throughput min/median/max. Heavy 5000-node configs halve the reps.
    Set BENCH_WIRE=1 to run the matrix over the real HTTP socket."""
    names = sys.argv[1:] or list(CONFIGS)
    reps_default = int(os.environ.get("BENCH_REPS", "3"))
    wire = os.environ.get("BENCH_WIRE", "0") == "1"
    out_path = os.path.join(os.path.dirname(__file__), "..",
                            "BENCH_WIRE_CONFIGS.json" if wire
                            else "BENCH_CONFIGS.json")
    mode = "a" if sys.argv[1:] else "w"  # full runs rewrite; partials append
    for name in names:
        import dataclasses

        # every bench row measures the same-config kernel-direct rate
        # in-process after the loop phase and records loop_kernel_ratio
        # — the adjudicating number for the ROADMAP "close the
        # loop-vs-kernel gap" target (full-loop >= 50% of kernel-direct
        # on Default-5000n)
        w = dataclasses.replace(CONFIGS[name], kernel_direct=True)
        if wire:
            w = dataclasses.replace(w, wire=True)
        # shadow parity sentinel knob (round 12): BENCH_SHADOW_SAMPLE
        # opts every row into the oracle replay at that rate (default 0 —
        # the sentinel is decision-inert and launch-free when off, so
        # baseline rows pay nothing)
        shadow_sample = float(os.environ.get("BENCH_SHADOW_SAMPLE", "0") or 0)
        if shadow_sample:
            w = dataclasses.replace(w, shadow_sample=shadow_sample)
        # heavy (>=5000-node) configs used to halve the reps; VERDICT r4
        # weak #2: never below 3 — a single sample is not a measurement
        reps = max(min(3, reps_default), reps_default // 2) \
            if w.num_nodes >= 5000 else reps_default
        print(f"=== {w.name}: {w.num_nodes} nodes, {w.num_pods} pods "
              f"(batch {w.max_batch}, reps {reps}, wire {wire}) on "
              f"{jax.devices()[0].platform}",
              file=sys.stderr, flush=True)
        runs = []
        for rep in range(reps):
            t0 = time.perf_counter()
            r = run_workload(w)
            wall = time.perf_counter() - t0
            line = r.to_dict()
            line["wall_s"] = round(wall, 1)
            runs.append(line)
            print(f"  rep {rep}: {line['throughput_avg']} pods/s "
                  f"({line['attempts_per_sec']} attempts/s)",
                  file=sys.stderr, flush=True)
        key = "attempts_per_sec" if w.saturating else "throughput_avg"
        vals = [r[key] for r in runs]
        line = next(r for r in runs if r[key] == _median(vals))
        line["reps"] = reps
        line["throughput_avg_runs"] = [r["throughput_avg"] for r in runs]
        line["attempts_per_sec_runs"] = [r["attempts_per_sec"] for r in runs]
        # per-rep session accounting: the rebuild storm was invisible
        # when only the median rep's dict survived (Preemption-PDB's
        # [62.4, 123.6, 123.1] reps hid 60+ rebuilds in rep 0)
        line["session_builds_runs"] = [
            r.get("session_builds") for r in runs
        ]
        line["session_rebuild_reasons_runs"] = [
            r.get("session_rebuild_reasons") for r in runs
        ]
        line["session_delta_applies_runs"] = [
            r.get("session_delta_applies") for r in runs
        ]
        # per-rep multipod/speculation accounting (round 9): same
        # reasoning as the session counters above — a conflict storm or
        # a speculation-miss cascade in one rep must not hide behind
        # the median rep's dict
        line["multipod_conflicts_runs"] = [
            r.get("multipod_conflicts") for r in runs
        ]
        line["conflict_replays_runs"] = [
            r.get("conflict_replays") for r in runs
        ]
        line["speculative_hits_runs"] = [
            r.get("speculative_hits") for r in runs
        ]
        line["speculative_misses_runs"] = [
            r.get("speculative_misses") for r in runs
        ]
        line["loop_kernel_ratio_runs"] = [
            r.get("loop_kernel_ratio") for r in runs
        ]
        # per-rep preemption planner-ladder accounting (round 10): the
        # device/fast/oracle split and what-if launch/fallback counts
        # must survive per rep — a fallback storm in one rep must not
        # hide behind the median rep's dict
        line["preemption_planner_paths_runs"] = [
            r.get("preemption_planner_paths") for r in runs
        ]
        line["whatif_launches_runs"] = [
            r.get("whatif_launches") for r in runs
        ]
        line["whatif_fallbacks_runs"] = [
            r.get("whatif_fallbacks") for r in runs
        ]
        # per-rep gang atomicity accounting (round 18): the Gang-* rows'
        # acceptance reads THESE — admitted * gang_size must equal
        # num_bound in every rep, and a rollback/rejection storm in one
        # rep must not hide behind the median rep's dict. Admission p99
        # is exact per rep (plugin sample buffer, not histogram buckets).
        line["gang_admitted_runs"] = [r.get("gang_admitted") for r in runs]
        line["gang_rejected_runs"] = [r.get("gang_rejected") for r in runs]
        line["gang_rollbacks_runs"] = [
            r.get("gang_rollbacks") for r in runs
        ]
        line["gang_preempted_runs"] = [
            r.get("gang_preempted") for r in runs
        ]
        line["gang_admission_p99_runs"] = [
            r.get("gang_admission_p99") for r in runs
        ]
        # per-rep stage-latency attribution (round 11): with KTPU_TRACE
        # on, each rep's per-stage p50/p99 breakdown survives — the chip
        # rerun reads WHICH stage owns the loop-vs-kernel gap per rep,
        # not a median rep's summary (None per rep with tracing off)
        line["stage_latency_runs"] = [
            r.get("stage_latency") for r in runs
        ]
        # per-rep completion-tax attribution (round 14): the assume
        # (cache writeback) and bind stages pulled out of each rep's
        # stage_latency so the chip rerun adjudicates the columnar
        # batched delta-apply directly, without unpacking the full
        # stage dict per rep (None with tracing off)
        line["assume_stage_runs"] = [
            (r.get("stage_latency") or {}).get("assume") for r in runs
        ]
        line["bind_stage_runs"] = [
            (r.get("stage_latency") or {}).get("bind") for r in runs
        ]
        # per-rep device-timeline attribution (round 16): with
        # KTPU_DEVTIME on, each rep's host<->device overlap ratio, its
        # kernel/transfer/compile device-seconds split, and its
        # dispatch-path recompile count survive — the chip rerun reads
        # where device time went PER REP (a compile storm in rep 0 must
        # not hide behind the median rep's dict). Always present:
        # 0.0/None/0 per rep with devtime off, mirroring
        # stage_latency_runs, so the schema is stable across knob sets.
        line["overlap_ratio_runs"] = [
            r.get("overlap_ratio") for r in runs
        ]
        line["device_time_runs"] = [
            r.get("device_time") for r in runs
        ]
        line["recompiles_runs"] = [
            r.get("recompiles") for r in runs
        ]
        # per-rep shadow parity accounting (round 12): at sample>0 the
        # chip rerun adjudicates drift from THESE counters — a drift
        # burst in one rep must not hide behind the median rep's dict
        line["shadow_sample"] = shadow_sample
        line["shadow_samples_runs"] = [
            r.get("shadow_samples") for r in runs
        ]
        line["shadow_drift_runs"] = [
            r.get("shadow_drift") for r in runs
        ]
        line["throughput_avg_min"] = min(r["throughput_avg"] for r in runs)
        line["throughput_avg_median"] = _median(
            [r["throughput_avg"] for r in runs]
        )
        line["wire"] = wire
        # artifact provenance (VERDICT r4 weak #2: append-mode rows with
        # mixed schemas made "the number" whichever row was last); only
        # stamped when the round is actually known — a wrong assertion
        # is worse than an absent field
        if os.environ.get("BENCH_ROUND"):
            line["round"] = int(os.environ["BENCH_ROUND"])
        print(json.dumps(line), flush=True)
        # append per config: a crash or timeout must not lose finished runs
        with open(out_path, mode) as f:
            f.write(json.dumps(line) + "\n")
        mode = "a"


if __name__ == "__main__":
    main()
