"""Fault drill: run the device-fault injection matrix against a live
TPU-backed scheduler over a churning cluster and report recovery.

Sibling of crash_drill.py (control-plane crashes); this one drills the
SCHEDULING pipeline's fault model: raising XLA dispatches, NaN/garbage
harvests, wedged device waits (watchdog), pipeline-worker kills
(supervised restart + FIFO drain-back), and kubelet deaths — all while a
ReplicaSet keeps the workload churning. Prints a recovery report (faults
injected, dispatch retries, ladder demotions/re-promotions, worker
restarts, final bind count) and exits nonzero on any lost or
double-bound pod.

Runs on CPU (the TPU backend rides the hoisted session there):

    JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python scripts/fault_drill.py
"""

import argparse
import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.api import apps, types as v1  # noqa: E402
from kubernetes_tpu.cluster import Cluster  # noqa: E402
from kubernetes_tpu.scheduler import metrics  # noqa: E402
from kubernetes_tpu.scheduler.apis.config import gang_configuration  # noqa: E402
from kubernetes_tpu.scheduler.plugins.coscheduling import (  # noqa: E402
    GROUP_LABEL,
    MIN_AVAILABLE_LABEL,
    pod_group,
)
from kubernetes_tpu.testing.chaos import ChaosMonkey  # noqa: E402
from kubernetes_tpu.testing.faults import (  # noqa: E402
    BindIntegrityChecker,
    FaultInjector,
    GangIntegrityChecker,
)


def wait_until(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def deployment(name: str, replicas: int) -> apps.Deployment:
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def counter_total(counter) -> float:
    return sum(val for _, val in counter.items())


def gang_deployment(name: str, size: int) -> apps.Deployment:
    """One Deployment == one self-healing gang: every replica carries the
    same group annotations (min-available == replicas), so a killed
    member's ReplicaSet replacement re-enters the SAME gang and
    re-completes it off the Coscheduling reserved index."""
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=size,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(
                    labels={"app": name},
                    annotations={
                        GROUP_LABEL: name,
                        MIN_AVAILABLE_LABEL: str(size),
                    },
                ),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def store_partial_gangs(client):
    """Authoritative end-of-drill scan, independent of the informer-fed
    checker: group every live pod by gang and report any TORN gang (some
    members bound, some not)."""
    pods, _ = client.pods.list(namespace="default")
    gangs = {}
    for p in pods:
        if p.metadata.deletion_timestamp is not None:
            continue
        group, min_available = pod_group(p)
        if not group or min_available <= 1:
            continue
        gangs.setdefault((p.metadata.namespace, group), []).append(
            bool(p.spec.node_name))
    return {
        gk: (sum(bound), len(bound))
        for gk, bound in gangs.items()
        if 0 < sum(bound) < len(bound)
    }


def gang_drill(args) -> int:
    """The gang atomicity matrix (all-or-nothing co-placement under
    faults): DIRECTED scenarios that each force a fresh admission wave
    and break something mid-wave — kill-member, crash-scheduler,
    failover (leader abdicates with the gang parked at Permit), and
    wedge-device — then a RANDOM gang-heavy chaos window. After every
    scenario the cluster must re-converge with ZERO torn gangs: a gang
    is always all-bound, all-waiting, or all-rolled-back."""
    rng = random.Random(args.seed)
    inj = FaultInjector()
    failures = []
    admitted0 = metrics.gang_admitted.value()

    with Cluster(
        n_nodes=args.nodes,
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
        fault_injector=inj,
        n_schedulers=2,
        election_opts=dict(
            lease_duration=1.5, renew_deadline=1.0,
            retry_period=0.05, fence_margin=0.3,
        ),
        scheduler_config=gang_configuration(
            permit_timeout=args.gang_permit_timeout),
    ) as c:
        for sched in c.schedulers:
            if sched.tpu is None:
                print("FAIL: gang drill needs the TPU scheduler backend")
                return 1
            sched.tpu.watchdog_timeout = args.watchdog
            sched.tpu.retry_base = 0.01
            sched.tpu.ladder._probe_interval = 0.1
            sched.tpu.ladder._probe_delay = 0.1
        checker = GangIntegrityChecker(grace=10.0).attach(
            c.kcm.informers.pods())
        bind_checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        n_gangs, k = args.gangs, args.gang_size
        dep_client = c.client.resource("deployments")
        for i in range(n_gangs):
            dep_client.create(gang_deployment(f"gang-{i}", k))

        def n_bound():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.spec.node_name
                       and p.metadata.deletion_timestamp is None)

        def converged(expect):
            return (n_bound() >= expect
                    and not checker.partial_gangs()
                    and not store_partial_gangs(c.client))

        total = n_gangs * k
        if not wait_until(lambda: converged(total), timeout=60):
            print(f"FAIL: initial gang convergence ({n_bound()}/{total} "
                  f"bound, partial={store_partial_gangs(c.client)})")
            return 1
        print(f"seeded: {n_gangs} gangs x {k} members on {args.nodes} "
              f"nodes (admitted: "
              f"{metrics.gang_admitted.value() - admitted0:.0f} waves)")

        def roll_gang(i):
            # delete every member of gang i: the RS recreates the whole
            # gang, forcing a FRESH admission wave through Permit
            pods, _ = c.client.pods.list(namespace="default")
            for p in pods:
                if (p.metadata.labels or {}).get("app") == f"gang-{i}":
                    c.client.pods.delete(
                        p.metadata.name, p.metadata.namespace)

        monkey = ChaosMonkey(c, rng=rng)  # manual driver for directed kinds

        def scenario_kill_member():
            # one bound member dies -> replacement re-completes; then a
            # WAITING member dies mid-wave -> the whole wave must roll
            # back (never a prefix) and re-form around the replacement
            monkey.do_one("kill-gang-member")
            roll_gang(rng.randrange(n_gangs))
            time.sleep(0.1)  # let the fresh wave start parking
            monkey.do_one("kill-gang-member")

        def scenario_crash_scheduler():
            inj.arm("kill-scheduler", shots=1)
            roll_gang(rng.randrange(n_gangs))

        def scenario_failover():
            roll_gang(rng.randrange(n_gangs))
            time.sleep(0.15)  # gang parks at Permit on the leader
            monkey.do_one("failover-scheduler")

        def scenario_wedge_device():
            inj.arm("wedge-wait", shots=1)
            roll_gang(rng.randrange(n_gangs))

        scenarios = [
            ("kill-member", scenario_kill_member),
            ("crash-scheduler-mid-gang", scenario_crash_scheduler),
            ("failover-mid-gang", scenario_failover),
            ("wedge-device-mid-gang", scenario_wedge_device),
        ]
        for name, fn in scenarios:
            before = metrics.gang_admitted.value()
            fn()
            ok = wait_until(lambda: converged(total), timeout=90)
            inj.disarm()
            waves = metrics.gang_admitted.value() - before
            partial = store_partial_gangs(c.client)
            print(f"scenario {name:26s} "
                  f"{'PASS' if ok else 'FAIL'} "
                  f"(re-admitted {waves:.0f} waves, bound {n_bound()}"
                  f"/{total}, partial={partial or 'none'})")
            if not ok:
                failures.append(
                    f"scenario {name}: no clean re-convergence "
                    f"({n_bound()}/{total} bound, partial={partial})")

        # random gang-heavy chaos window on top of the directed matrix
        monkey = ChaosMonkey(
            c, period=args.period, rng=rng,
            disruptions=[
                "kill-gang-member", "kill-gang-member", "gang-burst",
                "wedge-device", "crash-scheduler", "failover-scheduler",
                "delete-pod",
            ],
        )
        monkey.run()
        time.sleep(args.duration)
        monkey.stop()
        inj.disarm()
        monkey.restart_all_dead(timeout=30)

        # burst gangs are ownerless: all-waiting forever is legal for a
        # burst that lost a member, so convergence here is the DEPLOYMENT
        # gangs fully bound + zero torn gangs anywhere (checker + store)
        if not wait_until(lambda: converged(total), timeout=90):
            failures.append(
                f"post-chaos: {n_bound()}/{total} deployment members "
                f"bound, partial={store_partial_gangs(c.client)}")
        if checker.violations:
            failures.append(f"torn gangs (checker): {checker.violations}")
        if bind_checker.violations:
            failures.append(f"double binds: {bind_checker.violations}")
        final_partial = store_partial_gangs(c.client)
        if final_partial:
            failures.append(f"torn gangs (store scan): {final_partial}")

        by_kind = {}
        for d in monkey.history:
            by_kind[d.kind] = by_kind.get(d.kind, 0) + 1
        print("--- gang drill report ---")
        print(f"disruptions:      {by_kind}")
        print(f"faults injected:  {dict(inj.injected)}")
        print(f"gang admissions:  "
              f"{metrics.gang_admitted.value() - admitted0:.0f}")
        print(f"gang rollbacks:   "
              f"{ {k_[0]: int(val) for k_, val in metrics.gang_rollbacks.items()} }")
        print(f"gang rejected:    "
              f"{ {k_[0]: int(val) for k_, val in metrics.gang_rejected.items()} }")
        print(f"final bound:      {n_bound()} "
              f"({n_gangs} gangs x {k} + burst survivors)")

    if failures:
        print("FAIL:\n  " + "\n  ".join(failures))
        return 1
    print("PASS: every gang stayed all-bound / all-waiting / "
          "all-rolled-back through the full fault matrix")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=12)
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds of chaos")
    ap.add_argument("--period", type=float, default=0.25,
                    help="disruption period")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--watchdog", type=float, default=0.5,
                    help="dispatch watchdog (s)")
    ap.add_argument("--failover", action="store_true",
                    help="run TWO leader-elected scheduler instances and "
                         "add the failover/partition kinds to the mix — "
                         "device faults and leadership churn at once")
    ap.add_argument("--dump-trace", nargs="?", const="fault_drill_trace.json",
                    default="", metavar="PATH",
                    help="run with KTPU_TRACE=2, write the end-of-drill "
                         "flight-recorder snapshot to PATH, render it via "
                         "scripts/trace_report.py, and fail the drill if "
                         "any fault seam fired WITHOUT dumping or the "
                         "dump does not render")
    ap.add_argument("--devtime", nargs="?", const="fault_drill_devtime.json",
                    default="", metavar="PATH",
                    help="run with KTPU_DEVTIME>=1, assert every device "
                         "fault dumps the device timeline ALONGSIDE the "
                         "span ring, write the end-of-drill timeline to "
                         "PATH, and (with --dump-trace) gate the "
                         "trace_report timeline/span reconciliation")
    ap.add_argument("--gang", action="store_true",
                    help="run the gang atomicity matrix instead: directed "
                         "kill-member / crash-scheduler-mid-gang / "
                         "failover-mid-gang / wedge-device-mid-gang "
                         "scenarios plus a random gang-heavy chaos "
                         "window, exiting 1 on any torn gang")
    ap.add_argument("--gangs", type=int, default=3,
                    help="[--gang] number of deployment-backed gangs")
    ap.add_argument("--gang-size", type=int, default=4,
                    help="[--gang] members per gang (== min-available)")
    ap.add_argument("--gang-permit-timeout", type=float, default=3.0,
                    help="[--gang] Coscheduling permit timeout (s)")
    args = ap.parse_args()

    if args.gang:
        return gang_drill(args)

    from kubernetes_tpu.utils import devtime, tracing

    if args.dump_trace:
        # per-pod provenance on: the drill's dump must name the faulted
        # batch's bucket, rung and speculation state
        tracing.set_level(max(tracing.level(), 2))
    if args.devtime:
        devtime.set_level(max(devtime.level(), 1))
    rng = random.Random(args.seed)
    inj = FaultInjector()
    failures = []
    retries0 = metrics.dispatch_retries.value()
    restarts0 = counter_total(metrics.worker_restarts)
    faults0 = {k: val for k, val in metrics.device_faults.items()}
    dumps0 = counter_total(metrics.trace_dumps)
    sheds0 = counter_total(metrics.overload_sheds)
    restores0 = counter_total(metrics.overload_restores)
    ndumps0 = len(tracing.RECORDER.dump_history)
    dt_dumps0 = len(devtime.TIMELINE.dump_history)
    drift0 = counter_total(metrics.parity_drift)

    with Cluster(
        n_nodes=args.nodes,
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
        fault_injector=inj,
        n_schedulers=2 if args.failover else 1,
        election_opts=dict(
            lease_duration=1.5, renew_deadline=1.0,
            retry_period=0.05, fence_margin=0.3,
        ) if args.failover else None,
    ) as c:
        tpu = c.scheduler.tpu
        if tpu is None:
            print("FAIL: drill needs the TPU scheduler backend")
            return 1
        # either instance can hold the lease, so both backends get the
        # drill's aggressive fault-recovery timings
        for sched in c.schedulers:
            if sched.tpu is None:
                continue
            sched.tpu.watchdog_timeout = args.watchdog
            sched.tpu.retry_base = 0.01
            sched.tpu.ladder._probe_interval = 0.1
            sched.tpu.ladder._probe_delay = 0.1
        checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        c.client.resource("deployments").create(
            deployment("ha", args.replicas))

        def n_running():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.status.phase == "Running")

        if not wait_until(lambda: n_running() == args.replicas, timeout=60):
            print(f"FAIL: initial convergence "
                  f"({n_running()}/{args.replicas})")
            return 1
        print(f"seeded: {args.replicas} replicas on {args.nodes} nodes "
              f"(backend rung: {tpu.ladder.mode()})")

        kinds = [
            "wedge-device", "crash-scheduler", "overload",
            "kill-kubelet", "restart-kubelet", "delete-pod",
        ]
        if args.failover:
            kinds += ["failover-scheduler", "partition-scheduler"]
        monkey = ChaosMonkey(c, period=args.period, rng=rng,
                             disruptions=kinds)
        monkey.run()
        time.sleep(args.duration)
        monkey.stop()
        inj.disarm()  # end of the injection window
        monkey.restart_all_dead(timeout=30)

        # the ladder that matters is the lease holder's: a demoted
        # standby dispatches nothing, so its rung never re-probes
        tpu = c.active_scheduler.tpu
        if not wait_until(lambda: tpu.ladder.rung() >= tpu.ladder.top,
                          timeout=30):
            failures.append(
                f"ladder stuck at {tpu.ladder.mode()} after faults cleared")

        def converged():
            pods, _ = c.client.pods.list(namespace="default")
            running = [p for p in pods if p.status.phase == "Running"]
            return len(running) == args.replicas and len(pods) == args.replicas

        if not wait_until(converged, timeout=90):
            pods, _ = c.client.pods.list(namespace="default")
            lost = args.replicas - n_running()
            failures.append(
                f"lost pods: {lost} replicas missing after recovery "
                f"({len(pods)} pod objects)")
        if checker.violations:
            failures.append(f"double binds: {checker.violations}")

        pods, _ = c.client.pods.list(namespace="default")
        bound = sum(1 for p in pods if p.spec.node_name)
        by_kind = {}
        for d in monkey.history:
            by_kind[d.kind] = by_kind.get(d.kind, 0) + 1
        fault_delta = {
            k[0]: val - faults0.get(k, 0.0)
            for k, val in metrics.device_faults.items()
            if val - faults0.get(k, 0.0) > 0
        }

        print("--- recovery report ---")
        print(f"disruptions:      {by_kind}")
        print(f"faults injected:  {dict(inj.injected)}")
        print(f"faults recorded:  {fault_delta}")
        print(f"dispatch retries: "
              f"{metrics.dispatch_retries.value() - retries0:.0f}")
        print(f"worker restarts:  "
              f"{counter_total(metrics.worker_restarts) - restarts0:.0f}")
        print(f"ladder:           demotions={tpu.ladder.demotions} "
              f"re-promotions={tpu.ladder.promotions} "
              f"final={tpu.ladder.mode()}")
        ov = c.scheduler.overload
        print(f"overload:         "
              f"sheds={counter_total(metrics.overload_sheds) - sheds0:.0f} "
              f"restores="
              f"{counter_total(metrics.overload_restores) - restores0:.0f} "
              f"level={ov.level() if ov is not None else 'off'}")
        print(f"final bind count: {bound}/{args.replicas}")

        if args.dump_trace:
            # flight-recorder integrity: every fault seam that fired
            # must have dumped, and the end-of-drill snapshot must
            # render (chrome trace + stage report) — a seam that leaves
            # no triageable record fails the drill
            n_faults = sum(fault_delta.values())
            n_dumps = counter_total(metrics.trace_dumps) - dumps0
            seam_dumps = tracing.RECORDER.dump_history[ndumps0:]
            print(f"trace dumps:      {n_dumps:.0f} "
                  f"({sorted({d['reason'] for d in seam_dumps})})")
            if n_faults > 0 and n_dumps == 0:
                failures.append(
                    f"{n_faults:.0f} device faults recorded but no "
                    f"flight-recorder dump fired")
            # the shadow parity sentinel is a fault seam too: every
            # drift it counts must leave a paired shadow-drift ring
            # dump, or the drift is untriageable
            n_drift = counter_total(metrics.parity_drift) - drift0
            n_drift_dumps = sum(
                1 for d in seam_dumps if d["reason"] == "shadow-drift")
            if n_drift > 0 and n_drift_dumps == 0:
                failures.append(
                    f"{n_drift:.0f} parity drifts counted but no "
                    f"shadow-drift seam dump fired")
            tracing.dump("fault-drill-final", path=args.dump_trace,
                         faults=dict(inj.injected))
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            import trace_report

            if trace_report.render(args.dump_trace) != 0:
                failures.append(
                    f"trace_report could not render {args.dump_trace}")

        if args.devtime:
            # device-timeline integrity: a device fault must leave BOTH
            # halves of the story — dump_seam pairs the span-ring dump
            # with a timeline dump, so a fault with only one half is a
            # broken seam, not a rendering nit
            n_faults = sum(fault_delta.values())
            dt_seam_dumps = devtime.TIMELINE.dump_history[dt_dumps0:]
            print(f"devtime dumps:    {len(dt_seam_dumps)} "
                  f"({sorted({d['reason'] for d in dt_seam_dumps})})")
            if n_faults > 0 and not dt_seam_dumps:
                failures.append(
                    f"{n_faults:.0f} device faults recorded but no "
                    f"device-timeline dump fired")
            devtime.dump("fault-drill-final", path=args.devtime,
                         faults=dict(inj.injected))
            if args.dump_trace:
                sys.path.insert(0, os.path.dirname(
                    os.path.abspath(__file__)))
                import trace_report

                if trace_report.render(args.dump_trace,
                                       devtime_path=args.devtime) != 0:
                    failures.append(
                        f"trace_report timeline/span reconciliation "
                        f"failed for {args.devtime}")

    # wire smoke row: a small fan-out probe (8 watchers, both encodings
    # plus the mixed pass) — the single-serialize and eviction contracts
    # must hold even right after a drill's worth of global metric churn
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import probe_wire

    wire_rows, wire_failures = probe_wire.run_probe(
        [8], writers=2, events=120, slack=4.0, timeout=60)
    for row in wire_rows:
        print(f"wire probe:       {row['name']} "
              f"p99={row['delivery_p99_s'] * 1e3:.1f}ms "
              f"ser/event={row['serializations_per_event']:.2f}")
    failures.extend(f"wire probe: {f}" for f in wire_failures)

    if failures:
        print("FAIL:\n  " + "\n  ".join(failures))
        return 1
    print("PASS: pipeline survived the injection matrix "
          "(zero lost, zero double-bound)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
