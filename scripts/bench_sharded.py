"""Measure the sharded two-phase session against its single-device twins.

Two honest measurements (multi-chip TPU hardware is not available in this
environment — one v5e behind the tunnel):

  1. TPU, mesh=[1 chip]: ShardedPallasSession vs PallasSession vs
     HoistedSession per-pod cost at N nodes — the STRUCTURE tax of the
     per-pod two-phase scan (collectives are no-ops at 1 device, so this
     isolates what the scan-over-pods shape costs vs the single-launch
     kernel and the jnp hoisted scan).
  2. CPU, 8 virtual devices: ShardedPallasSession at 1/2/4/8 shards at
     5k/10k/20k nodes — the SCALING shape (emulated collectives; wall
     clock is only comparable within this table, never to TPU numbers).

Writes one JSON line per row to BENCH_SHARDED.json.

Usage: python scripts/bench_sharded.py tpu|cpu
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

mode = sys.argv[1] if len(sys.argv) > 1 else "tpu"
if mode == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
if mode == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from kubernetes_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()

from __graft_entry__ import _synth_session_inputs  # noqa: E402
from kubernetes_tpu.ops.hoisted import HoistedSession  # noqa: E402
from kubernetes_tpu.ops.pallas_scan import PallasSession  # noqa: E402
from kubernetes_tpu.ops.sharded_scan import ShardedPallasSession  # noqa: E402
from kubernetes_tpu.parallel.sharded import make_mesh  # noqa: E402
from kubernetes_tpu.testing.synth import (  # noqa: E402
    synth_cluster,
    synth_pending_pods,
)

OUT = os.path.join(os.path.dirname(__file__), "..", "BENCH_SHARDED.json")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def measure(sess_cls, cluster, arrays, templates, batch, reps, **kw):
    sess = sess_cls(cluster, templates, **kw)
    decide = sess_cls.decisions
    warm = arrays[:batch]
    t0 = time.perf_counter()
    decide(sess.schedule(warm))
    compile_s = time.perf_counter() - t0
    rates = []
    for r in range(reps):
        lo = batch * (1 + r)
        b = arrays[lo:lo + batch]
        if len(b) < batch:
            break
        t0 = time.perf_counter()
        decide(sess.schedule(b))
        dt = time.perf_counter() - t0
        rates.append(len(b) / dt)
    rates.sort()
    med = rates[(len(rates) - 1) // 2] if rates else 0.0
    return med, rates, compile_s


def emit(row):
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main():
    reps = int(os.environ.get("BENCH_REPS", "3"))
    if mode == "tpu":
        n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
        batch = 1024
        nodes, init_pods = synth_cluster(n_nodes, pods_per_node=2)
        pending = synth_pending_pods(batch * (1 + reps), spread=True)
        cluster, arrays, templates = _synth_session_inputs(
            nodes, init_pods, pending)
        mesh = make_mesh(n_devices=1)
        for name, cls, kw in (
            ("pallas", PallasSession, {}),
            ("hoisted", HoistedSession, {}),
            ("sharded2p-1dev", ShardedPallasSession, {"mesh": mesh}),
        ):
            med, rates, comp = measure(
                cls, cluster, arrays, templates, batch, reps, **kw)
            log(f"tpu {name}: median {med:.0f} pods/s "
                f"({['%.0f' % r for r in rates]}, compile {comp:.1f}s)")
            emit({
                "bench": "sharded-structure-tax", "platform": "tpu",
                "session": name, "nodes": n_nodes, "batch": batch,
                "pods_per_sec_median": round(med, 1),
                "pods_per_sec_runs": [round(r, 1) for r in rates],
                "compile_s": round(comp, 1), "reps": len(rates),
                "round": int(os.environ.get("BENCH_ROUND", "0")) or None,
            })
    else:
        batch = 256
        for n_nodes in (5000, 10000, 20000):
            nodes, init_pods = synth_cluster(n_nodes, pods_per_node=1)
            pending = synth_pending_pods(batch * (1 + reps), spread=True)
            cluster, arrays, templates = _synth_session_inputs(
                nodes, init_pods, pending)
            rows = [("hoisted-1dev", HoistedSession, {})]
            for nsh in (1, 2, 4, 8):
                rows.append((f"sharded2p-{nsh}dev", ShardedPallasSession,
                             {"mesh": make_mesh(n_devices=nsh)}))
            for name, cls, kw in rows:
                med, rates, comp = measure(
                    cls, cluster, arrays, templates, batch, reps, **kw)
                log(f"cpu {n_nodes}n {name}: median {med:.0f} pods/s "
                    f"(compile {comp:.1f}s)")
                emit({
                    "bench": "sharded-scaling-shape", "platform": "cpu",
                    "session": name, "nodes": n_nodes, "batch": batch,
                    "pods_per_sec_median": round(med, 1),
                    "pods_per_sec_runs": [round(r, 1) for r in rates],
                    "compile_s": round(comp, 1), "reps": len(rates),
                    "round": int(os.environ.get("BENCH_ROUND", "0")) or None,
                })


if __name__ == "__main__":
    main()
