"""Adjudicate the completion tax: batched columnar delta-apply vs the
per-pod object writeback, across harvest batch sizes.

Each trial replays the production assume stage end to end: a fresh
SchedulerCache (columnar on or off) with a TPUBackend-shaped echo
listener over a real ClusterEncoding, landing PODS pods in
assume_pods() harvests of size B. The per-pod object path pays the
round-11 triple tax — NodeInfo writeback, then the listener assume-echo
routing through enc.add_pod, which for an already-encoded key nets a
FULL remove_pod + re-add (two row encodes, two volume refcount
round-trips). The columnar path lands the NodeInfo writebacks plus ONE
vectorized columnar delta and ONE batched on_assume_pods whose echo
collapses to a stored-object swap. The decision-time enc.add_pod (the
harvest's device-side apply) happens OUTSIDE the timer in both modes —
only the assume stage is measured.

Parity is asserted per run: both modes must end with identical dump()
contents, per-node NodeInfo aggregates, encoding pod placements, and
(columnar mode) columnar rows that recompute exactly from the NodeInfo
aggregates.

Chip-runnable but device-free (cache + encoding are pure host state):
the same numbers adjudicate on a TPU host and on CPU CI.

Usage: python scripts/probe_assume.py
Env: PROBE_NODES (1000), PROBE_PODS (3000), PROBE_BATCHES
     (comma list, default 1,32,128,512,1024), PROBE_REPS (3).

Output: one JSON line per (mode, batch-size) with wall seconds and
pods/s, then a summary speedup table on stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from kubernetes_tpu.api import types as v1  # noqa: E402
from kubernetes_tpu.models.encoding import ClusterEncoding  # noqa: E402
from kubernetes_tpu.scheduler.internal.cache import (  # noqa: E402
    CacheListener,
    SchedulerCache,
)
from kubernetes_tpu.testing.synth import make_node, make_pod  # noqa: E402


class EchoListener(CacheListener):
    """The TPUBackend's assume-echo shape, minus the device: every
    placement was already applied to the encoding at harvest time
    (_apply_decisions_locked) and recorded in session_assumed; the
    cache's assume then echoes back. Per-pod path: on_add_pod ->
    enc.add_pod (remove + re-add for the already-held key). Batched
    path: on_assume_pods -> enc.swap_pod_object."""

    def __init__(self, enc: ClusterEncoding):
        self.enc = enc
        self.session_assumed = set()

    def on_add_pod(self, pod, node_name):
        key = (pod.metadata.namespace, pod.metadata.name, node_name)
        if key in self.session_assumed:
            self.session_assumed.discard(key)
            self.enc.add_pod(pod, node_name)

    def on_assume_pods(self, items):
        assumed = self.session_assumed
        swap = self.enc.swap_pod_object
        for pod, node_name in items:
            key = (pod.metadata.namespace, pod.metadata.name, node_name)
            if key in assumed and swap(v1.pod_key(pod), pod, node_name):
                assumed.discard(key)
            else:
                self.on_add_pod(pod, node_name)


def _mk_pods(n_pods: int, n_nodes: int):
    pods = []
    for i in range(n_pods):
        p = make_pod(f"probe-{i}", cpu="100m", memory="128Mi",
                     node_name=f"node-{i % n_nodes}")
        pods.append(p)
    return pods


def _node_aggregates(cache):
    out = {}
    for name in sorted(n.metadata.name for n in cache.dump()[0]):
        ni = cache._nodes[name]
        out[name] = (
            ni.requested.milli_cpu, ni.requested.memory,
            ni.requested.ephemeral_storage,
            ni.non_zero_requested.milli_cpu,
            ni.non_zero_requested.memory,
            len(ni.pods),
        )
    return out


def _assert_columnar_rows(cache):
    """Columnar rows must recompute exactly from the object NodeInfos."""
    for name, (cpu, mem, eph, nz_cpu, nz_mem, npods) in \
            _node_aggregates(cache).items():
        i = cache._col_index[name]
        row = (
            int(cache._col_req[i, 0]), int(cache._col_req[i, 1]),
            int(cache._col_req[i, 2]), int(cache._col_nz[i, 0]),
            int(cache._col_nz[i, 1]), int(cache._col_counts[i, 0]),
        )
        assert row == (cpu, mem, eph, nz_cpu, nz_mem, npods), (
            f"columnar row for {name} diverged: {row} != "
            f"{(cpu, mem, eph, nz_cpu, nz_mem, npods)}"
        )


def _setup(columnar: bool, nodes, n_pods: int):
    cache = SchedulerCache(columnar=columnar)
    enc = ClusterEncoding()
    # pre-size like the harness: without this the pod table overflows
    # into _rebuild_needed and every echo add_pod degrades to a cheap
    # dict update — hiding exactly the row-encode tax being probed
    enc.reserve(pods=int(n_pods * 2))
    enc.set_cluster(nodes, [])
    enc.rebuild()  # live arrays: the echo must hit the row-encode path
    listener = EchoListener(enc)
    cache.add_listener(listener)
    for n in nodes:
        cache.add_node(n)
    return cache, enc, listener


def _trial(columnar: bool, nodes, pods, batch: int) -> float:
    cache, enc, listener = _setup(columnar, nodes, len(pods))
    wall = 0.0
    for off in range(0, len(pods), batch):
        harvest = pods[off:off + batch]
        # harvest-time device apply — NOT the measured stage
        for p in harvest:
            listener.session_assumed.add(
                (p.metadata.namespace, p.metadata.name, p.spec.node_name))
            enc.add_pod(p, p.spec.node_name)
        t0 = time.perf_counter()
        ok = cache.assume_pods(harvest)
        wall += time.perf_counter() - t0
        assert all(ok)
    assert not listener.session_assumed, "unechoed assumes left behind"
    if columnar:
        _assert_columnar_rows(cache)
    return wall


def main() -> None:
    n_nodes = int(os.environ.get("PROBE_NODES", "1000"))
    n_pods = int(os.environ.get("PROBE_PODS", "3000"))
    batches = [
        int(b) for b in os.environ.get(
            "PROBE_BATCHES", "1,32,128,512,1024").split(",")
    ]
    reps = int(os.environ.get("PROBE_REPS", "3"))
    nodes = [make_node(f"node-{i}") for i in range(n_nodes)]
    pods = _mk_pods(n_pods, n_nodes)

    # cross-mode parity once up front: same pod stream, both modes,
    # identical end state (cache AND encoding placements)
    ref, ref_enc, ref_l = _setup(False, nodes, n_pods)
    col, col_enc, col_l = _setup(True, nodes, n_pods)
    for c, e, l in ((ref, ref_enc, ref_l), (col, col_enc, col_l)):
        for p in pods:
            l.session_assumed.add(
                (p.metadata.namespace, p.metadata.name, p.spec.node_name))
            e.add_pod(p, p.spec.node_name)
        assert all(c.assume_pods(list(pods)))
    assert {k: ent[1] for k, ent in ref_enc._pods.items()} == \
        {k: ent[1] for k, ent in col_enc._pods.items()}, \
        "encoding placements diverged between modes"
    ref_nodes, ref_pods = ref.dump()
    col_nodes, col_pods = col.dump()
    assert [n.metadata.name for n in ref_nodes] == \
        [n.metadata.name for n in col_nodes], "dump node order diverged"
    assert [v1.pod_key(p) for p in ref_pods] == \
        [v1.pod_key(p) for p in col_pods], "dump pod set diverged"
    assert _node_aggregates(ref) == _node_aggregates(col), \
        "NodeInfo aggregates diverged between modes"
    assert ref.foreign_mutations() == col.foreign_mutations()
    _assert_columnar_rows(col)
    print("parity: ok (dump, aggregates, foreign_mutations, "
          "columnar rows)", file=sys.stderr)

    speedups = {}
    for batch in batches:
        walls = {}
        for mode, columnar in (("object", False), ("columnar", True)):
            best = min(
                _trial(columnar, nodes, pods, batch) for _ in range(reps)
            )
            walls[mode] = best
            print(json.dumps({
                "mode": mode, "batch": batch, "nodes": n_nodes,
                "pods": n_pods, "wall_s": round(best, 5),
                "pods_per_sec": round(n_pods / best, 1),
            }), flush=True)
        speedups[batch] = walls["object"] / walls["columnar"]
    print("\nbatched columnar speedup over per-pod object writeback:",
          file=sys.stderr)
    for batch, s in speedups.items():
        print(f"  B={batch:>5}: {s:.2f}x", file=sys.stderr)


if __name__ == "__main__":
    main()
