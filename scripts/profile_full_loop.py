"""Statistical all-thread profiler for the FULL scheduler loop.

Samples sys._current_frames() every ~4ms during the measured window of a
mid-scale workload (default: 2000 nodes / 4096 pods, batch 1024) and
aggregates inclusive time per function per thread-role — a poor-man's
py-spy (not installed here) that sees the scheduler thread, the binder
pool, and the informer dispatch thread at once.

Usage: python scripts/profile_full_loop.py [nodes] [pods] [batch]
"""
import collections
import os
import sys
import threading
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
P = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
B = int(sys.argv[3]) if len(sys.argv) > 3 else 1024

from kubernetes_tpu.perf import harness  # noqa: E402
from kubernetes_tpu.perf.harness import PodTemplate, Workload  # noqa: E402

samples = collections.Counter()  # (thread_name, func_id) -> count
stack_samples = collections.Counter()  # leaf-up 4-frame stack -> count
sampling = threading.Event()
done = threading.Event()
n_samples = [0]

_names = {}


def _thread_names():
    for t in threading.enumerate():
        _names[t.ident] = t.name
    return _names


def sampler():
    while not done.is_set():
        if not sampling.is_set():
            time.sleep(0.01)
            continue
        names = _thread_names()
        me = threading.get_ident()
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            name = names.get(tid, str(tid))
            # normalize thread-pool/ephemeral names to roles
            if name.startswith("binder"):
                role = "binder"
            elif name.startswith("Thread-"):
                role = "informer/other"
            else:
                role = name
            f = frame
            leaf = f"{os.path.basename(f.f_code.co_filename)}:{f.f_code.co_name}"
            stack = []
            while f is not None and len(stack) < 5:
                stack.append(
                    f"{os.path.basename(f.f_code.co_filename)}:{f.f_code.co_name}"
                )
                f = f.f_back
            samples[(role, leaf)] += 1
            stack_samples[(role, tuple(stack))] += 1
        n_samples[0] += 1
        time.sleep(0.004)


# hook the harness measured window: patch time.sleep-based loop by toggling
# `sampling` around run_workload's measured phase. Simplest reliable hook:
# wrap Scheduler.resume (second resume = measured phase start).
from kubernetes_tpu.scheduler.scheduler import Scheduler  # noqa: E402

_resumes = [0]
_orig_resume = Scheduler.resume


def patched_resume(self):
    _resumes[0] += 1
    if _resumes[0] >= 2:  # measured-phase resume
        sampling.set()
    return _orig_resume(self)


Scheduler.resume = patched_resume

t = threading.Thread(target=sampler, daemon=True)
t.start()

WIRE = os.environ.get("PROFILE_WIRE", "0") == "1"
GANG = int(os.environ.get("PROFILE_GANG", "0"))  # gang size; 0 = spread
CHURN = os.environ.get("PROFILE_CHURN", "0") == "1"
PVC = os.environ.get("PROFILE_PVC", "")  # "zonal" | "csi" | "migrated"
if PVC:
    w = Workload(
        f"profile-pvc-{N}n-{P}p", num_nodes=N,
        num_init_pods=min(2048, P), num_pods=P,
        init_template=PodTemplate(with_pvc=PVC),
        template=PodTemplate(with_pvc=PVC),
        max_batch=B, timeout=900.0, wire=WIRE,
    )
elif CHURN:
    w = Workload(
        f"profile-churn-{N}n-{P}p", num_nodes=N, num_init_pods=1000,
        num_pods=P,
        init_template=PodTemplate(spread_zone=True),
        template=PodTemplate(spread_zone=True),
        second_template=PodTemplate(cpu="8", memory="64Gi"),
        second_every=3,
        max_batch=B, timeout=600.0, stall_stop=15.0, saturating=True,
        wire=WIRE,
    )
elif GANG:
    w = Workload(
        f"profile-gang-{N}n-{P}p", num_nodes=N, num_init_pods=min(2048, P),
        num_pods=P, gang_size=GANG,
        init_template=PodTemplate(extended={"example.com/gpu": "1"}),
        template=PodTemplate(extended={"example.com/gpu": "1"}),
        node_extended={"example.com/gpu": "8"},
        max_batch=B, timeout=600.0, wire=WIRE,
    )
else:
    w = Workload(
        f"profile-{N}n-{P}p", num_nodes=N, num_init_pods=min(2048, P),
        num_pods=P, init_template=PodTemplate(spread_zone=True),
        template=PodTemplate(spread_zone=True), max_batch=B, timeout=600.0,
        wire=WIRE,
    )
t0 = time.perf_counter()
r = harness.run_workload(w)
sampling.clear()
done.set()
wall = time.perf_counter() - t0

print(f"\n=== {r.name}: {r.throughput_avg} pods/s avg "
      f"(p50 {r.throughput_p50}, p90 {r.throughput_p90}), "
      f"{r.num_bound}/{P} bound, wall {wall:.1f}s, "
      f"{n_samples[0]} sample sweeps")

by_role = collections.Counter()
for (role, leaf), c in samples.items():
    by_role[role] += c
total = sum(by_role.values()) or 1
print("\n-- samples by thread role --")
for role, c in by_role.most_common():
    print(f"  {role:<18}{c:7d}  {100*c/total:5.1f}%")

print("\n-- top leaves per role --")
for role, _ in by_role.most_common(4):
    print(f"  [{role}]")
    role_total = by_role[role] or 1
    leaves = collections.Counter(
        {leaf: c for (rr, leaf), c in samples.items() if rr == role}
    )
    for leaf, c in leaves.most_common(14):
        print(f"    {100*c/role_total:5.1f}%  {leaf}")

print("\n-- top stacks (all roles) --")
for (role, stack), c in stack_samples.most_common(25):
    print(f"  {100*c/total:5.1f}% [{role}] {' < '.join(stack)}")
