"""Where does bench.py's 1.1ms/pod go? Split: encode / schedule(dispatch)
/ device wait / harvest (add_pod)."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import copy
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
B = 1024
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(3 * B, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)

def encode_batch(pods):
    return [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pods]

arrays0 = encode_batch(pending)
templates, seen = [], set()
for a in arrays0:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
sess = HoistedSession(enc.device_state(), templates)
# warm compile + state
ys = sess.schedule(encode_batch(pending[:B]))
for p, b in zip(pending[:B], HoistedSession.decisions(ys)):
    if b >= 0: enc.add_pod(p, enc.node_names[b])

for it in range(2):
    batch = pending[(it+1)*B:(it+2)*B]
    t0 = time.perf_counter(); arrays = encode_batch(batch); t_enc = time.perf_counter()-t0
    t0 = time.perf_counter(); ys = sess.schedule(arrays); t_disp = time.perf_counter()-t0
    t0 = time.perf_counter(); dec = HoistedSession.decisions(ys); t_wait = time.perf_counter()-t0
    t0 = time.perf_counter()
    for p, b in zip(batch, dec):
        if b >= 0: enc.add_pod(p, enc.node_names[b])
    t_harv = time.perf_counter()-t0
    tot = t_enc+t_disp+t_wait+t_harv
    print(f"iter{it}: encode={t_enc*1e3:6.1f}ms dispatch={t_disp*1e3:6.1f}ms "
          f"wait={t_wait*1e3:6.1f}ms harvest={t_harv*1e3:6.1f}ms "
          f"total={tot*1e3:6.1f}ms ({tot/B*1e3:.2f} ms/pod)")
