"""Measure the HTTP wire tax ONCE (VERDICT r2 missing #6): the same
workload through the full scheduler loop, in-proc vs over the real HTTP
apiserver (apiserver/http.py socket + RemoteAPIServer clients — the
boundary the reference's scheduler_perf always crosses, util.go:61).

Writes one JSON line per mode to BENCH_WIRE.json.

Usage: python scripts/bench_wire.py [nodes] [pods]
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()

from kubernetes_tpu.perf.harness import (  # noqa: E402
    PodTemplate,
    Workload,
    run_workload,
)


def main() -> None:
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    n_pods = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    out_path = os.path.join(os.path.dirname(__file__), "..", "BENCH_WIRE.json")
    lines = []
    for wire in (False, True):
        w = Workload(
            f"WireTax-{n_nodes}n-{'http' if wire else 'inproc'}",
            num_nodes=n_nodes, num_init_pods=2048, num_pods=n_pods,
            init_template=PodTemplate(spread_zone=True),
            template=PodTemplate(spread_zone=True),
            max_batch=1024, timeout=900.0, wire=wire,
        )
        r = run_workload(w)
        line = r.to_dict()
        line["wire"] = wire
        print(json.dumps(line), flush=True)
        lines.append(line)
    inproc = next(ln for ln in lines if not ln["wire"])
    http = next(ln for ln in lines if ln["wire"])
    summary = {
        "name": "WireTaxSummary",
        "inproc_pods_per_sec": inproc["throughput_avg"],
        "http_pods_per_sec": http["throughput_avg"],
        "wire_tax_pct": round(
            100.0 * (1 - http["throughput_avg"]
                     / max(inproc["throughput_avg"], 1e-9)), 1),
    }
    print(json.dumps(summary), flush=True)
    with open(out_path, "w") as f:
        for ln in lines + [summary]:
            f.write(json.dumps(ln) + "\n")


if __name__ == "__main__":
    main()
