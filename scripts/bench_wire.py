"""Measure the HTTP wire tax (VERDICT r2 missing #6) and the watch
fan-out wire path (ISSUE 18): the same workload through the full
scheduler loop in-proc vs over the real HTTP apiserver, plus a
WireFanout-{100,1000}w family driving N raw-socket watchers x M writers
through the single-serialize broadcast hub per encoding.

Every row runs BENCH_REPS times (default 3) and carries the MEDIAN
rep's detail plus per-rep `<metric>_runs` lists — including
serializations_per_event, the counter that adjudicates the
"serialize once per encoding, never per watcher" claim on real runs.

Writes one JSON line per row to BENCH_WIRE.json.

Usage: python scripts/bench_wire.py [nodes] [pods]
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()

import probe_wire  # noqa: E402
from kubernetes_tpu.apiserver.http import (  # noqa: E402
    watch_evictions,
    wire_encode_bytes,
    wire_events,
    wire_serializations,
)
from kubernetes_tpu.perf.harness import (  # noqa: E402
    PodTemplate,
    Workload,
    run_workload,
)

# (watchers, events-per-rep): event volume scaled down with fan-out so
# one rep stays bounded on the 1-core bench box (frames = events x
# watchers either way: 30k and 150k frames per rep respectively)
FANOUT_POINTS = ((100, 300), (1000, 150))


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2]


def _counters() -> dict:
    return {
        "serializations": sum(v for _, v in wire_serializations.items()),
        "events": wire_events.value(),
        "encode_bytes": sum(v for _, v in wire_encode_bytes.items()),
        "evictions": watch_evictions.value(),
    }


def _wiretax_rows(n_nodes: int, n_pods: int, reps: int) -> list:
    rows = []
    for wire in (False, True):
        w = Workload(
            f"WireTax-{n_nodes}n-{'http' if wire else 'inproc'}",
            num_nodes=n_nodes, num_init_pods=2048, num_pods=n_pods,
            init_template=PodTemplate(spread_zone=True),
            template=PodTemplate(spread_zone=True),
            max_batch=1024, timeout=900.0, wire=wire,
        )
        runs = []
        for rep in range(reps):
            before = _counters()
            r = run_workload(w)
            after = _counters()
            line = r.to_dict()
            ev = after["events"] - before["events"]
            line["wire_events"] = ev
            line["serializations_per_event"] = round(
                (after["serializations"] - before["serializations"])
                / ev, 4) if ev else 0.0
            line["wire_encode_bytes"] = \
                after["encode_bytes"] - before["encode_bytes"]
            line["watch_evictions"] = \
                after["evictions"] - before["evictions"]
            runs.append(line)
            print(f"  rep {rep}: {line['throughput_avg']} pods/s "
                  f"(ser/event {line['serializations_per_event']})",
                  file=sys.stderr, flush=True)
        vals = [r["throughput_avg"] for r in runs]
        line = dict(next(r for r in runs if r["throughput_avg"]
                         == _median(vals)))
        line["wire"] = wire
        line["reps"] = reps
        for key in ("throughput_avg", "pod_scheduling_p99",
                    "serializations_per_event", "wire_encode_bytes",
                    "watch_evictions"):
            line[f"{key}_runs"] = [r[key] for r in runs]
        rows.append(line)
        print(json.dumps(line), flush=True)
    return rows


def _fanout_rows(reps: int) -> list:
    rows = []
    for watchers, events in FANOUT_POINTS:
        for binary in (False, True):
            enc = "binary" if binary else "json"
            runs = []
            for rep in range(reps):
                row = probe_wire.run_pass(
                    watchers, writers=2, events=events, binary=binary,
                    timeout=240)
                runs.append(row)
                print(f"  rep {rep}: {row['name']} "
                      f"p99={row['delivery_p99_s'] * 1e3:.1f}ms "
                      f"frames/s={row['frames_per_sec']:.0f}",
                      file=sys.stderr, flush=True)
            vals = [r["frames_per_sec"] for r in runs]
            line = dict(next(r for r in runs if r["frames_per_sec"]
                             == _median(vals)))
            line["name"] = f"WireFanout-{watchers}w-{enc}"
            line["headline_metric"] = "delivery_p99_s"
            line["reps"] = reps
            for key in ("delivery_p99_s", "frames_per_sec",
                        "serializations_per_event", "encode_bytes",
                        "evictions"):
                line[f"{key}_runs"] = [r[key] for r in runs]
            rows.append(line)
            print(json.dumps(line), flush=True)
    return rows


def main() -> None:
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    n_pods = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    reps = int(os.environ.get("BENCH_REPS", "3"))
    out_path = os.path.join(os.path.dirname(__file__), "..",
                            "BENCH_WIRE.json")
    lines = _wiretax_rows(n_nodes, n_pods, reps)
    inproc = next(ln for ln in lines if not ln["wire"])
    http = next(ln for ln in lines if ln["wire"])
    lines += _fanout_rows(reps)
    summary = {
        "name": "WireTaxSummary",
        "inproc_pods_per_sec": inproc["throughput_avg"],
        "http_pods_per_sec": http["throughput_avg"],
        "wire_tax_pct": round(
            100.0 * (1 - http["throughput_avg"]
                     / max(inproc["throughput_avg"], 1e-9)), 1),
        # adjudication context: the tax ratio is box-shaped — on a
        # single-core host every wire thread (fan-out encode, socket
        # syscalls, client decode) competes with the scheduler for the
        # GIL, so the ratio reads worse there than on a multi-core box
        # where delivery overlaps dispatch
        "session_kind": http.get("session_kind"),
        "cpus": os.cpu_count(),
    }
    print(json.dumps(summary), flush=True)
    with open(out_path, "w") as f:
        for ln in lines + [summary]:
            f.write(json.dumps(ln) + "\n")


if __name__ == "__main__":
    main()
