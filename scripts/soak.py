"""Endurance soak: sustained-churn ChaosMonkey run in the PRODUCTION
shape, gated by invariant monitors.

This is the "run it as it would be in production, not as a drill"
harness (ROADMAP wire/soak item): shadow parity sentinel sampling at the
production rate (KTPU_SHADOW_SAMPLE=0.01), flight recorder ON
(KTPU_TRACE=1), pipeline depth 2, a ReplicaSet keeping the workload
churning, and a ChaosMonkey mixing workload churn with the `overload`
disruption (completion-worker stall waves / synthetic event bursts) so
the host-overload monitor's shed→restore cycle is exercised for real.

The invariant monitors (kubernetes_tpu/testing/invariants.py) read
/metricsz — the operator surface, not scheduler internals — and assert:

  zero shadow drift            scheduler_parity_drift_total flat
  zero expired assumes         scheduler_cache_expired_assumes_total flat
  zero lost / double binds     BindIntegrityChecker + final convergence
  stage p99 flatness           windowed p99 of the scheduling-attempt
                               histogram, first third vs last third
  bounded RSS/fd/thread growth process_* gauges, first vs last third
  queue returns to baseline    scheduler_pending_pods after the chaos
  no assume outlives its TTL   scheduler_cache_oldest_assume_seconds

Any violation exits nonzero and writes a triage bundle (trace-ring dump
+ metrics snapshots + report.json). The run must also show at least one
FULL shed→restore cycle under the injected overload (pass
--allow-no-shed to waive, e.g. on hardware fast enough to never shed).

CI/chip gate contract:  python scripts/soak.py --seconds 60
exits 0 iff every invariant held AND a full shed→restore cycle ran.
"""

import argparse
import os
import random
import sys
import time

# the PRODUCTION shape, resolved before kubernetes_tpu imports: shadow
# sentinel at the production sample rate, flight recorder on, CPU lane
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
os.environ.setdefault("KTPU_SHADOW_SAMPLE", "0.01")
os.environ.setdefault("KTPU_TRACE", "1")
# overload water marks scaled to a CPU soak: the stall wave (0.6 s per
# batch) must out-age the high mark for the shed dwell, and calm churn
# must restore within one inter-wave gap
os.environ.setdefault("KTPU_OVERLOAD_FIFO_AGE", "0.3")
os.environ.setdefault("KTPU_OVERLOAD_SHED_DWELL", "2")
os.environ.setdefault("KTPU_OVERLOAD_RESTORE_DWELL", "4")
os.environ.setdefault("KTPU_OVERLOAD_COOLDOWN", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.api import apps, types as v1  # noqa: E402
from kubernetes_tpu.cluster import Cluster  # noqa: E402
from kubernetes_tpu.scheduler import metrics  # noqa: E402
from kubernetes_tpu.scheduler.apis.config import (  # noqa: E402
    gang_configuration,
)
from kubernetes_tpu.scheduler.plugins.coscheduling import (  # noqa: E402
    GROUP_LABEL,
    MIN_AVAILABLE_LABEL,
)
from kubernetes_tpu.testing import invariants as inv  # noqa: E402
from kubernetes_tpu.testing.chaos import ChaosMonkey  # noqa: E402
from kubernetes_tpu.testing.faults import (  # noqa: E402
    BindIntegrityChecker,
    FaultInjector,
    GangIntegrityChecker,
)


def wait_until(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def deployment(name: str, replicas: int) -> apps.Deployment:
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def gang_deployment(name: str, size: int) -> apps.Deployment:
    """One Deployment == one self-healing gang (see fault_drill.py):
    every replica carries the same group annotations, so a chaos-killed
    member's replacement re-enters and re-completes the SAME gang."""
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=size,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(
                    labels={"app": name},
                    annotations={
                        GROUP_LABEL: name,
                        MIN_AVAILABLE_LABEL: str(size),
                    },
                ),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def build_suite(checker: BindIntegrityChecker, assume_ttl: float,
                watchers: int = 0,
                gang_checker: GangIntegrityChecker = None):
    extra = []
    if gang_checker is not None:
        # gang atomicity through the WHOLE window: a gang torn past the
        # checker's grace (some members bound, siblings not) is a
        # violation even if it later heals
        extra.append(inv.Callback(
            "zero-torn-gangs", lambda: list(gang_checker.violations)))
    if watchers:
        # wire fan-out SLI (ISSUE 18): with N watchers riding the hub
        # through the whole chaos window, the delivery p99 must stay
        # flat — a rising tail here is the broadcast path drifting
        # toward eviction under churn. Generous ratio/floor: the
        # 1-core box schedules ~N writer threads per event burst.
        extra.append(inv.HistogramP99Flat(
            "apiserver_watch_delivery_seconds",
            ratio=8.0, floor=0.5, label="watch-delivery-p99-flat"))
    return inv.InvariantSuite(extra + [
        inv.CounterFlat("scheduler_parity_drift_total",
                        label="zero-shadow-drift"),
        inv.CounterFlat("scheduler_cache_expired_assumes_total",
                        label="zero-expired-assumes"),
        inv.Callback("zero-double-binds",
                     lambda: list(checker.violations)),
        inv.HistogramP99Flat(
            "scheduler_pod_scheduling_attempt_duration_seconds",
            ratio=8.0, floor=0.02, label="stage-p99-flat"),
        inv.BoundedGrowth("process_resident_memory_bytes",
                          max_frac=0.35, label="rss-growth"),
        inv.BoundedGrowth("process_open_fds", max_abs=32,
                          label="fd-growth"),
        inv.BoundedGrowth("process_threads", max_abs=16,
                          label="thread-growth"),
        inv.GaugeBaseline("scheduler_pending_pods", slack=4,
                          label="queue-returns-to-baseline"),
        inv.GaugeBaseline("apiserver_watchers", slack=0,
                          label="watchers-return-to-baseline"),
        inv.GaugeCeiling("scheduler_cache_oldest_assume_seconds",
                         ceiling=assume_ttl + 5.0,
                         label="no-assume-outlives-ttl"),
    ])


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=60.0,
                    help="chaos window duration (hours-capable)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=12)
    ap.add_argument("--period", type=float, default=0.25,
                    help="disruption period")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--sample-every", type=float, default=0.5,
                    help="invariant /metricsz sample cadence")
    ap.add_argument("--bundle-dir", default="soak_failure_bundle",
                    help="where the triage bundle lands on failure")
    ap.add_argument("--allow-no-shed", action="store_true",
                    help="do not require a full shed->restore cycle "
                         "(hardware fast enough to never overload)")
    ap.add_argument("--watchers", type=int, default=0,
                    help="attach N wire watchers (half binary, half "
                         "JSON raw sockets) to an HTTP hub over the "
                         "cluster's apiserver and hold the watch "
                         "delivery p99 flat for the whole window")
    ap.add_argument("--gangs", type=int, default=0,
                    help="run N deployment-backed gangs through the "
                         "chaos window (Coscheduling permit gate on, "
                         "kill-gang-member/gang-burst in the mix) and "
                         "hold the gang atomicity invariant: never a "
                         "torn gang, before or after recovery")
    ap.add_argument("--gang-size", type=int, default=4,
                    help="members per gang (== min-available)")
    ap.add_argument("--gang-permit-timeout", type=float, default=3.0,
                    help="Coscheduling permit timeout (s)")
    args = ap.parse_args()

    rng = random.Random(args.seed)
    inj = FaultInjector()
    inj.stall_delay = 0.6  # one stalled batch must out-age the high mark
    failures = []

    with Cluster(
        n_nodes=args.nodes,
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
        fault_injector=inj,
        scheduler_config=(
            gang_configuration(permit_timeout=args.gang_permit_timeout)
            if args.gangs else None
        ),
    ) as c:
        sched = c.scheduler
        tpu = sched.tpu
        if tpu is None or sched.overload is None:
            print("FAIL: soak needs the TPU scheduler backend with the "
                  "overload monitor enabled")
            return 1
        tpu.watchdog_timeout = 0.5
        tpu.retry_base = 0.01
        tpu.ladder._probe_interval = 0.1
        tpu.ladder._probe_delay = 0.1
        checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        gang_checker = None
        if args.gangs:
            gang_checker = GangIntegrityChecker(grace=10.0).attach(
                c.kcm.informers.pods())
        c.client.resource("deployments").create(
            deployment("soak", args.replicas))
        for i in range(args.gangs):
            c.client.resource("deployments").create(
                gang_deployment(f"gang-{i}", args.gang_size))
        # the soak's convergence target: every DEPLOYMENT-owned pod
        # (soak replicas + gang members); ownerless gang-burst pods are
        # excluded — an all-waiting burst that lost a member is a legal
        # terminal state, and they are swept before the final baseline
        expect = args.replicas + args.gangs * args.gang_size

        def owned(p):
            return not p.metadata.name.startswith("chaos-gang-")

        def n_running():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods
                       if owned(p) and p.status.phase == "Running")

        if not wait_until(lambda: n_running() == expect, timeout=60):
            print(f"FAIL: initial convergence "
                  f"({n_running()}/{expect})")
            return 1
        print(f"seeded: {args.replicas} replicas + {args.gangs} gangs x "
              f"{args.gang_size} on {args.nodes} nodes, "
              f"shadow_sample={tpu.shadow_sample}, depth="
              f"{sched.pipeline_depth}, rung={tpu.ladder.mode()}")

        wire_hub = drainer = None
        if args.watchers:
            # PRODUCTION wire shape: N reflector-like watchers on a real
            # HTTP hub over the SAME store, attached BEFORE the baseline
            # sample so fd/thread/watcher baselines include them
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            import probe_wire
            from kubernetes_tpu.apiserver.http import HTTPAPIServer

            wire_hub = HTTPAPIServer(c.api).start()
            drainer = probe_wire._Drainer()
            half = args.watchers // 2
            probe_wire._attach_watchers(
                wire_hub.address, half, True, drainer)
            probe_wire._attach_watchers(
                wire_hub.address, args.watchers - half, False, drainer)
            if not wait_until(
                    lambda: wire_hub.watcher_count >= args.watchers,
                    timeout=60):
                print(f"FAIL: only {wire_hub.watcher_count}/"
                      f"{args.watchers} wire watchers attached")
                return 1
            print(f"wire watchers:     {args.watchers} attached "
                  f"({half} binary, {args.watchers - half} json)")

        suite = build_suite(checker, assume_ttl=sched.cache._ttl,
                            watchers=args.watchers,
                            gang_checker=gang_checker)
        suite.sample()  # baseline BEFORE the chaos window

        # churn-heavy mix (delete-pod thrice-weighted keeps batches
        # flowing so the monitor always has completion ticks to
        # observe), overload every ~6 disruptions on average; with
        # --gangs the gang kinds join so admission waves keep forming
        # and getting broken mid-flight
        mix = [
            "delete-pod", "delete-pod", "delete-pod",
            "overload", "wedge-device", "crash-scheduler",
        ]
        if args.gangs:
            mix += ["kill-gang-member", "kill-gang-member", "gang-burst"]
        monkey = ChaosMonkey(c, period=args.period, rng=rng,
                             disruptions=mix)
        monkey.run()
        deadline = time.monotonic() + args.seconds
        while time.monotonic() < deadline:
            time.sleep(args.sample_every)
            suite.sample()
        monkey.stop()
        inj.disarm()
        monkey.restart_all_dead(timeout=30)

        if args.gangs:
            # sweep the ownerless burst gangs: a burst that lost a member
            # to chaos is legally all-waiting forever, which would pin
            # the queue above its baseline — atomicity was already
            # monitored live; the baseline checks judge the OWNED world
            for p in c.client.pods.list(namespace="default")[0]:
                if not owned(p) and p.metadata.deletion_timestamp is None:
                    try:
                        c.client.pods.delete(
                            p.metadata.name, p.metadata.namespace)
                    except Exception:  # noqa: BLE001 — racing deletes
                        pass

        ov = sched.overload

        def churn_tick():
            pods, _ = c.client.pods.list(namespace="default")
            live = [p for p in pods
                    if p.metadata.deletion_timestamp is None]
            if live:
                p = rng.choice(live)
                c.client.pods.delete(
                    p.metadata.name, p.metadata.namespace)

        if ov.cycles < 1 and not args.allow_no_shed:
            # the random mix never completed a full cycle inside the
            # window: run one DIRECTED wave so the report always shows
            # the machinery working end-to-end (stall until shed, clear,
            # churn until restored)
            print("no full shed->restore cycle in the random window; "
                  "running a directed overload wave")
            inj.arm("stall-completion", shots=50)

            deadline = time.monotonic() + 30
            while ov.level() == 0 and time.monotonic() < deadline:
                churn_tick()
                time.sleep(0.3)
                suite.sample()
            inj.disarm("stall-completion")
            deadline = time.monotonic() + 30
            while ov.level() > 0 and time.monotonic() < deadline:
                churn_tick()
                time.sleep(0.3)
                suite.sample()

        if ov.level() > 0:
            # recovery drain: the pressure sources are gone (chaos
            # stopped, injectors disarmed) but the monitor only
            # re-evaluates while the scheduler is doing work, and
            # restore-dwell needs consecutive calm ticks — churn
            # lightly until every lever restores. Bounded, so a wedged
            # monitor still fails the levers-still-shed check below.
            # With --watchers at 1000 the event drain keeps a small box
            # hot through the whole chaos window, so restore
            # legitimately lands in this tail rather than mid-chaos.
            print(f"recovery drain: {ov.shed_names()} still shed; "
                  f"churning until restored")
            deadline = time.monotonic() + 45
            while ov.level() > 0 and time.monotonic() < deadline:
                churn_tick()
                time.sleep(0.3)
                suite.sample()

        if not wait_until(lambda: tpu.ladder.rung() >= tpu.ladder.top,
                          timeout=30):
            failures.append(
                f"ladder stuck at {tpu.ladder.mode()} after faults cleared")

        def converged():
            pods, _ = c.client.pods.list(namespace="default")
            mine = [p for p in pods if owned(p)]
            running = [p for p in mine if p.status.phase == "Running"]
            return (len(running) == expect and len(mine) == expect
                    and (gang_checker is None
                         or not gang_checker.partial_gangs()))

        if not wait_until(converged, timeout=90):
            failures.append(
                f"lost pods: {expect - n_running()} owned pods "
                f"missing after recovery")
        if gang_checker is not None:
            final_partial = gang_checker.partial_gangs()
            if final_partial:
                failures.append(f"torn gangs at soak end: {final_partial}")
        # settle, then close the invariant window (queue/watcher
        # baselines are judged on the LAST sample)
        time.sleep(2.0)
        violations = suite.finish()
        failures.extend(violations)

        if ov.cycles < 1 and not args.allow_no_shed:
            failures.append(
                "no full shed->restore cycle ran (overload never "
                "triggered; tune KTPU_OVERLOAD_* or --allow-no-shed)")
        if ov.level() > 0:
            failures.append(
                f"levers still shed at soak end: {ov.shed_names()}")

        by_kind = {}
        for d in monkey.history:
            by_kind[d.kind] = by_kind.get(d.kind, 0) + 1
        print("--- soak report ---")
        print(f"window:            {args.seconds:.0f}s chaos, "
              f"{len(suite.samples)} invariant samples")
        print(f"disruptions:       {by_kind}")
        print(f"faults injected:   {dict(inj.injected)}")
        print(f"overload cycles:   {ov.cycles} full shed->restore "
              f"(final level {ov.level()})")
        for t, action, what, sig in ov.history:
            print(f"  {action:7s} {what:16s} fifo_age={sig['fifo_age']} "
                  f"queue={sig['queue_depth']}")
        if args.gangs:
            rollbacks = {
                k[0]: int(val)
                for k, val in metrics.gang_rollbacks.items() if val
            }
            print(f"gang admissions:   "
                  f"{metrics.gang_admitted.value():.0f} waves, "
                  f"rollbacks={rollbacks}")
        shadow = inv.total(suite.samples[-1][1],
                           "scheduler_shadow_samples_total")
        skips = inv.total(suite.samples[-1][1],
                          "scheduler_shadow_skips_total")
        print(f"shadow samples:    {shadow:.0f} audited, {skips:.0f} "
              f"voided stale-basis (drift must be 0: see invariants)")
        print("invariants:        "
              + ("ALL HELD" if not violations else "VIOLATED"))
        for v in violations:
            print(f"  VIOLATION: {v}")
        if wire_hub is not None:
            evicted = inv.total(suite.samples[-1][1],
                                "apiserver_watch_evictions_total")
            print(f"wire watchers:     {wire_hub.watcher_count} still "
                  f"attached at exit, {evicted:.0f} evictions, "
                  f"{drainer.bytes_rx / 1e6:.1f}MB drained")
            drainer.stop()
            wire_hub.stop()

        if failures:
            # queue post-mortem: for every entry still parked in the
            # scheduling queue, what does the apiserver think that pod
            # IS right now? (a Running/absent pod here = a stale entry)
            live = {}
            for p in c.client.pods.list(namespace="default")[0]:
                live[f"{p.metadata.namespace}/{p.metadata.name}"] = {
                    "phase": p.status.phase,
                    "node": p.spec.node_name,
                    "deleting": p.metadata.deletion_timestamp is not None,
                }
            active, backoff, unsched = sched.queue.depths()
            queue_dump = {
                "depths": {"active": active, "backoff": backoff,
                           "unschedulable": unsched},
                "entries": [
                    {"key": f"{p.metadata.namespace}/{p.metadata.name}",
                     "live": live.get(
                         f"{p.metadata.namespace}/{p.metadata.name}",
                         "ABSENT")}
                    for p in sched.queue.pending_pods()
                ],
            }
            bundle = suite.bundle(
                args.bundle_dir, extra={
                    "failures": failures,
                    "disruptions": by_kind,
                    "queue": queue_dump,
                    "overload_history": [
                        (a, w, s) for _, a, w, s in ov.history],
                })
            print(f"triage bundle:     {bundle}/ (trace.json, "
                  f"metrics_first/last.json, report.json)")

    if failures:
        print("FAIL:\n  " + "\n  ".join(failures))
        return 1
    print("PASS: production-shape soak held every invariant "
          "(zero drift, zero lost binds, flat p99s, no leaks) "
          f"with {metrics.overload_level.value():.0f} levers shed at exit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
