"""Probe: delta-apply vs full-rebuild cost, plus the per-event
classification a synthetic churn trace gets from the session-delta
classifier (ISSUE 5 tooling satellite).

Builds a TPU-backend cluster directly (no apiserver — this measures the
backend, not the loop), warms a live session, then replays a synthetic
churn trace shaped like the preemption benchmarks' event mix: victim
delete echoes, foreign batchable adds, affinity-pod adds, node
heartbeats, and allocatable-only node updates. For each event it prints
the classification (carry-delta / prologue-patch / structural /
heartbeat-noop), then times

  * one fused delta apply for the whole queued batch, vs
  * one full session rebuild (what every one of those events cost
    before this round),

both on the live device. Chip-runnable as-is; degrades to CPU exactly
like fault_drill.py (the backend rides the hoisted session there):

    JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python scripts/probe_session_deltas.py
"""

import argparse
import os
import random
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402,F401

from kubernetes_tpu.api import types as v1  # noqa: E402
from kubernetes_tpu.scheduler import metrics  # noqa: E402
from kubernetes_tpu.scheduler.internal.cache import SchedulerCache  # noqa: E402
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend  # noqa: E402
from kubernetes_tpu.testing.synth import make_node, make_pod  # noqa: E402


def counter_total(counter) -> float:
    return sum(val for _, val in counter.items())


def build_cluster(n_nodes: int):
    cache = SchedulerCache()
    be = TPUBackend()
    cache.add_listener(be)
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"node-{i}",
            labels={v1.LABEL_HOSTNAME: f"node-{i}", "zone": f"zone-{i % 3}"},
        ))
    return cache, be


def spread_pod(name, cpu="100m", node=""):
    return make_pod(
        name, namespace="default", cpu=cpu, memory="64Mi",
        labels={"app": "perf"},
        constraints=[v1.TopologySpreadConstraint(
            max_skew=1, topology_key=v1.LABEL_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            label_selector=v1.LabelSelector(match_labels={"app": "perf"}),
        )],
        node_name=node,
    )


def anti_pod(name, node=""):
    return make_pod(
        name, namespace="default", cpu="100m", memory="64Mi",
        labels={"app": "anti"},
        affinity=v1.Affinity(pod_anti_affinity=v1.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "anti"}),
                    topology_key=v1.LABEL_HOSTNAME,
                )
            ]
        )),
        node_name=node,
    )


def classify_and_queue(be, event, payload) -> str:
    """Replay one trace event against the backend and report which class
    the classifier gave it (reading the queue/session state around the
    listener call — the probe's whole point is showing the taxonomy)."""
    sess = be._session
    n_deltas = len(be._deltas)
    event(payload)
    if be._session is not sess or be._session is None:
        return "structural  (session teardown)"
    if len(be._deltas) == n_deltas:
        return "noop        (gated: heartbeat / never-encoded)"
    kind = be._deltas[-1]["kind"]
    if kind == "node-alloc":
        return "prologue-patch (alloc column)"
    return f"carry-delta ({kind})"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=200)
    ap.add_argument("--warm-pods", type=int, default=256)
    ap.add_argument("--events", type=int, default=64)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    rng = random.Random(args.seed)
    cache, be = build_cluster(args.nodes)
    # pre-size the pod table like the perf harness does: walking the
    # capacity ladder mid-trace is a (legitimate) structural rebuild and
    # would pollute the classification histogram this probe is after
    be.enc.reserve(pods=2 * (args.warm_pods + 3 * args.events) + 64)
    print(f"platform={jax.devices()[0].platform} nodes={args.nodes} "
          f"(session kind follows the ladder top: "
          f"{'pallas' if be.use_pallas else 'hoisted'})")

    # warm: build the session + compile the dispatch shapes; confirm the
    # binds into the cache (the informer echo the real loop produces —
    # swallowed by the assume-echo gate, and the precondition for their
    # later delete echoes to reach the listener at all)
    t0 = time.perf_counter()
    res = be.schedule_many(
        [spread_pod(f"warm-{i}") for i in range(args.warm_pods)])
    n_bound = sum(1 for _, n in res if n)
    victims = []
    for p, node in res:
        if not node:
            continue
        confirmed = spread_pod(p.metadata.name, node=node)
        cache.add_pod(confirmed)
        if len(victims) < args.events:
            victims.append(confirmed)
    print(f"warm batch: {n_bound}/{args.warm_pods} bound in "
          f"{time.perf_counter() - t0:.2f}s "
          f"(session={type(be._session).__name__})")
    trace = []
    for i in range(args.events):
        r = rng.random()
        if r < 0.45 and victims:
            v = victims.pop(rng.randrange(len(victims)))
            trace.append(("victim-delete-echo", cache.remove_pod, v))
        elif r < 0.70:
            trace.append((
                "foreign-batchable-add", cache.add_pod,
                spread_pod(f"foreign-{i}",
                           node=f"node-{rng.randrange(args.nodes)}"),
            ))
        elif r < 0.80:
            trace.append((
                "affinity-pod-add", cache.add_pod,
                anti_pod(f"anti-{i}",
                         node=f"node-{rng.randrange(args.nodes)}"),
            ))
        elif r < 0.90:
            j = rng.randrange(args.nodes)
            trace.append((
                "node-heartbeat", cache.update_node,
                make_node(f"node-{j}", labels={
                    v1.LABEL_HOSTNAME: f"node-{j}",
                    "zone": f"zone-{j % 3}"}),
            ))
        else:
            j = rng.randrange(args.nodes)
            trace.append((
                "node-alloc-update", cache.update_node,
                make_node(f"node-{j}", cpu="8", labels={
                    v1.LABEL_HOSTNAME: f"node-{j}",
                    "zone": f"zone-{j % 3}"}),
            ))

    print(f"\n--- per-event classification ({len(trace)} events) ---")
    by_class = {}
    for name, fn, payload in trace:
        cls = classify_and_queue(be, fn, payload)
        by_class[cls] = by_class.get(cls, 0) + 1
        print(f"  {name:24s} -> {cls}")
        if be._session is None:
            # keep the probe measuring the delta path: rebuild and go on
            be.schedule_many([spread_pod(f"rewarm-{name}-{len(by_class)}")])
    print("\nclassification histogram:")
    for cls, n in sorted(by_class.items()):
        print(f"  {n:4d}  {cls}")

    # timing: fused delta apply (whole queue, one launch) vs full
    # rebuild. Round 0 pays the delta-scan compile for this event-count
    # bucket; round 1 is the steady-state number (the compile is cached
    # persistently, like every other dispatch shape).
    be._apply_session_deltas_locked()  # land the trace leftovers first
    burst = max(8, args.events // 2)
    t_apply = 0.0
    for rnd in range(2):
        if be._session is None:
            be.schedule_many([spread_pod(f"rewarm-t{rnd}")])
        for i in range(burst):
            cache.add_pod(spread_pod(
                f"burst{rnd}-{i}", node=f"node-{rng.randrange(args.nodes)}"))
        queued = len(be._deltas)
        t0 = time.perf_counter()
        be._apply_session_deltas_locked()
        if be._session is not None:
            jax.block_until_ready(be._session._carry)
        t_apply = time.perf_counter() - t0
    t0 = time.perf_counter()
    with be._lock:
        be._invalidate_session("probe-timing")
        be._session = be._build_session()
    t_rebuild = time.perf_counter() - t0

    applies = counter_total(metrics.session_delta_applies)
    rebuilds = counter_total(metrics.session_rebuilds)
    print("\n--- cost ---")
    print(f"delta apply ({queued} queued events, one fused launch, "
          f"warm): {t_apply * 1e3:.1f} ms")
    print(f"full session rebuild (what each event used to cost):     "
          f"{t_rebuild * 1e3:.1f} ms")
    if t_apply > 0:
        print(f"ratio: {t_rebuild / max(t_apply, 1e-9):.1f}x per flush "
              f"(and the old path paid it per EVENT)")
    print(f"counters: delta_applies={applies:.0f} "
          f"session_rebuilds={rebuilds:.0f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
