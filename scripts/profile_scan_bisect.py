import os, sys, time, functools
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import CARRY_KEYS, _step
from kubernetes_tpu.ops.kernel import DEFAULT_WEIGHTS
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N, B = 5000, 50
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pods = synth_pending_pods(B, spread=True)
for q in pods: pe.encode(q)
c = enc.device_state()
arrays = [{k: v for k, v in pe.encode(q).items() if not k.startswith("_")} for q in pods]
stacked = {k: jnp.asarray(np.stack([np.asarray(a[k]) for a in arrays])) for k in arrays[0]}
slots = np.asarray([enc._pod_free[-1 - i] for i in range(B)], np.int32)
xs = {"pod": stacked, "pidx": jnp.asarray(slots), "valid": jnp.ones(B, bool)}
static_c = {k: v for k, v in c.items() if k not in CARRY_KEYS}
carry = {k: c[k] for k in CARRY_KEYS}
key = tuple(sorted(DEFAULT_WEIGHTS.items()))

def bench(name, jf, *args):
    out = jf(*args); jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = jf(*args); jax.block_until_ready(out)
    print(f"{name}: {(time.perf_counter()-t0)*1000/B:.3f}ms/pod", flush=True)

# A: args-passed static_c (exactly _scan_batch)
@functools.partial(jax.jit, static_argnames=("weights_key",))
def variant_args(static_c, carry, xs, weights_key):
    step = functools.partial(_step, static_c, dict(weights_key))
    return jax.lax.scan(step, carry, xs)
bench("A_args_static_c", variant_args, static_c, carry, xs, key)

# B: closure static_c, same _step
@jax.jit
def variant_closure(carry, xs):
    step = functools.partial(_step, static_c, DEFAULT_WEIGHTS)
    return jax.lax.scan(step, carry, xs)
bench("B_closure_static_c", variant_closure, carry, xs)
