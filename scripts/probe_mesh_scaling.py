"""Weak-scaling probe for the mesh scale-out backend: pods/s and
per-host RSS vs node-axis shard count, parity-asserted.

For each shard count the probe forks a fresh interpreter (RSS is
process-wide — per-shard-count memory is only honest from a clean
process), builds a TPUBackend over a mesh of that many devices, and
drives schedule_many over a synthetic cluster:

  * parity prefix: the first PROBE_PARITY pods are also scheduled
    through a single-device (hoisted) backend over the same cluster —
    decisions must be BIT-IDENTICAL before any number is recorded
    (the scale-out contract: sharding is a performance property);
  * throughput: pods/s over the measured schedule_many batches on the
    mesh backend;
  * memory: ru_maxrss after the run, plus the session's per-host node
    rows (Npl = Nps/nsh) — the bound that makes 100k nodes fit.

CPU-runnable: the devices are simulated
(XLA_FLAGS=--xla_force_host_platform_device_count, set below before
jax imports). On a real pod slice the same probe measures ICI.

Usage: python scripts/probe_mesh_scaling.py
Env: PROBE_NODES (20000), PROBE_PODS (512), PROBE_PARITY (32),
     PROBE_SHARDS (comma list, default 2,4,8).

Output: one JSON row per shard count on stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

NODES = int(os.environ.get("PROBE_NODES", "20000"))
PODS = int(os.environ.get("PROBE_PODS", "512"))
PARITY = int(os.environ.get("PROBE_PARITY", "32"))
SHARDS = [int(s) for s in
          os.environ.get("PROBE_SHARDS", "2,4,8").split(",")]


def _vmrss_mb() -> float:
    """Current VmRSS from /proc (0.0 where /proc is unavailable)."""
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return round(int(ln.split()[1]) / 1024, 1)
    except OSError:
        pass
    return 0.0


def _child(nsh: int) -> None:
    """One measurement in THIS process (spawned by main): mesh backend
    at nsh shards, single-device parity prefix, one JSON row."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_ENABLE_X64"] = "1"
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={max(nsh, 8)}"
        )
    import resource
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from kubernetes_tpu.api import types as v1
    from kubernetes_tpu.parallel.sharded import make_mesh
    from kubernetes_tpu.scheduler.internal.cache import SchedulerCache
    from kubernetes_tpu.scheduler.tpu_backend import TPUBackend
    from kubernetes_tpu.testing.synth import make_node, make_pod

    def build(mesh):
        cache = SchedulerCache()
        be = TPUBackend(mesh=mesh)
        cache.add_listener(be)
        for i in range(NODES):
            cache.add_node(make_node(
                f"node-{i}",
                labels={v1.LABEL_HOSTNAME: f"node-{i}",
                        v1.LABEL_ZONE: f"zone-{i % 3}"}))
        be.enc.reserve(pods=int(PODS * 1.5))
        return be

    def pods(prefix, n):
        return [make_pod(f"{prefix}-{i}", cpu="100m", memory="64Mi")
                for i in range(n)]

    be = build(make_mesh(n_devices=nsh))
    got = [n for _, n in be.schedule_many(pods("parity", PARITY))]
    sess = be._session
    assert type(sess).__name__ == "ShardedPallasSession", type(sess)

    # parity prefix vs the single-device reference — weak-scaling rows
    # are only recorded for a backend that still schedules identically
    ref_be = build(None)
    ref = [n for _, n in ref_be.schedule_many(pods("parity", PARITY))]
    assert got == ref, f"nsh={nsh} parity broke: {got[:8]} vs {ref[:8]}"
    del ref_be

    batch = 128
    t0 = time.perf_counter()
    done = 0
    for start in range(0, PODS, batch):
        n = min(batch, PODS - start)
        res = be.schedule_many(pods(f"m{start}", n))
        done += sum(1 for _, nm in res if nm is not None)
    dt = time.perf_counter() - t0

    row = {
        "nsh": nsh,
        "nodes": NODES,
        "pods": PODS,
        "bound": done,
        "pods_per_sec": round(done / dt, 2) if dt else 0.0,
        # peak RSS (NB: includes the single-device parity reference
        # built above) and current RSS after the measured run — the
        # second is the honest per-host steady-state number
        "maxrss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "rss_mb": _vmrss_mb(),
        # per-host node rows: the session splits Nps rows over nsh
        # shards; this is the array bound that scales the node axis out
        "node_rows_total": int(sess.Nps),
        "node_rows_per_host": int(sess.Npl),
        "parity_prefix": PARITY,
        "parity": "ok",
    }
    assert sess.Npl * nsh == sess.Nps
    print(json.dumps(row), flush=True)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child(int(sys.argv[2]))
        return
    for nsh in SHARDS:
        print(f"=== nsh={nsh}: {NODES} nodes, {PODS} pods",
              file=sys.stderr, flush=True)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(nsh)],
            stdout=subprocess.PIPE, text=True, check=True)
        sys.stdout.write(proc.stdout)
        sys.stdout.flush()


if __name__ == "__main__":
    main()
