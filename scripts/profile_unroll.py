import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
import kubernetes_tpu.ops.hoisted as H
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N, B = 5000, 512
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(2 * B, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)
arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pending]
templates, seen = [], set()
for a in arrays:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
# honest mode
poison = jax.numpy.arange(4) + 1; jax.block_until_ready(poison); np.asarray(poison)
for unroll in (1, 8, 32):
    os.environ["KTPU_SCAN_UNROLL"] = str(unroll)
    H._session_scan._clear_cache()
    sess = HoistedSession(enc.device_state(), templates)
    t0 = time.perf_counter()
    jax.block_until_ready(sess.schedule(arrays[:B])["best"])
    t_compile = time.perf_counter() - t0
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(sess.schedule(arrays[:B])["best"])
        ts.append(time.perf_counter() - t0)
    print(f"unroll={unroll:3d}: {min(ts)*1e3:8.1f}ms ({min(ts)/B*1e3:6.3f} ms/pod) "
          f"compile={t_compile:.0f}s")
