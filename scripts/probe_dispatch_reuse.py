"""Bisect the ~580ms fixed pallas dispatch cost on the real chip:
AOT persistent-executable reuse vs plain jit dispatch, and the
batch-size slope (fixed cost = extrapolation of wall(B=128) vs
wall(B=1024) to B=0).

Usage (on the chip): python scripts/probe_dispatch_reuse.py
Env: PROBE_NODES (5000), PROBE_BATCHES (8).

Every timing is taken AFTER one device->host read (the tunnel's
deferred mode makes un-synced timings enqueue-cost illusions — see
PERF_NOTES "The axon tunnel's two execution modes").
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()

import numpy as np  # noqa: E402

from kubernetes_tpu.models.encoding import ClusterEncoding  # noqa: E402
from kubernetes_tpu.models.pod_encoder import PodEncoder  # noqa: E402
from kubernetes_tpu.ops.hoisted import template_fingerprint  # noqa: E402
from kubernetes_tpu.ops.pallas_scan import PallasSession  # noqa: E402
from kubernetes_tpu.testing.synth import (  # noqa: E402
    synth_cluster,
    synth_pending_pods,
)


def _measure(aot: bool, nodes, init_pods, pending, batches, B):
    os.environ["KTPU_PALLAS_AOT"] = "1" if aot else "0"
    enc = ClusterEncoding()
    enc.set_cluster(nodes, init_pods)
    pe = PodEncoder(enc)
    arrays = [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        for p in pending
    ]
    templates, seen = [], set()
    for a in arrays:
        fp = template_fingerprint(a)
        if fp not in seen:
            seen.add(fp)
            templates.append(a)
    # multipod_k=1: no conflict-suffix replay loop here — probe the
    # one-pod-per-step dispatch path
    sess = PallasSession(enc.device_state(), templates, multipod_k=1)
    # warm: compile + flip the tunnel into honest sync mode
    PallasSession.decisions(sess.schedule(arrays[:B]))
    dts = []
    for i in range(1, batches + 1):
        t0 = time.perf_counter()
        ys = sess.schedule(arrays[i * B:(i + 1) * B])
        PallasSession.decisions(ys)  # blocks: one dispatch, end to end
        dts.append(time.perf_counter() - t0)
    return dts


def main() -> None:
    n_nodes = int(os.environ.get("PROBE_NODES", "5000"))
    batches = int(os.environ.get("PROBE_BATCHES", "8"))
    nodes, init_pods = synth_cluster(n_nodes, pods_per_node=2)
    out = {}
    for B in (128, 1024):
        pending = synth_pending_pods((batches + 1) * B, spread=True)
        for aot in (False, True):
            dts = _measure(aot, nodes, init_pods, pending, batches, B)
            med = sorted(dts)[len(dts) // 2]
            out[(B, aot)] = med
            print(f"B={B:5d} aot={int(aot)}: median {med * 1000:.1f}ms "
                  f"/dispatch ({1000 * med / B:.2f}ms/pod); "
                  f"all {[round(d * 1000) for d in dts]}",
                  flush=True)
    for aot in (False, True):
        # wall(B) = fixed + B*marginal -> solve from the two batch sizes
        a, b = out[(128, aot)], out[(1024, aot)]
        marginal = (b - a) / (1024 - 128)
        fixed = a - 128 * marginal
        print(f"aot={int(aot)}: fixed ~{fixed * 1000:.0f}ms, "
              f"marginal ~{marginal * 1e6:.0f}us/pod")


if __name__ == "__main__":
    main()
