"""Isolate lax.scan overhead vs carry-update overhead (dev tool)."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops import kernel as K
from kubernetes_tpu.ops.batch import CARRY_KEYS
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
B = 50
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pods = synth_pending_pods(B, spread=True)
for q in pods: pe.encode(q)
c = enc.device_state()
arrays = [{k: v for k, v in pe.encode(q).items() if not k.startswith("_")} for q in pods]
stacked = {k: jnp.asarray(np.stack([np.asarray(a[k]) for a in arrays])) for k in arrays[0]}

def bench(name, jf, *args):
    out = jf(*args); jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = jf(*args); jax.block_until_ready(out)
    print(f"{name}: {(time.perf_counter()-t0)*1000/B:.2f}ms/pod", flush=True)

# 1: scan, no carry mutation (pure map over pods)
@jax.jit
def scan_nocarry(c, xs):
    def step(carry, p):
        out = K.schedule_pod(c, p)
        return carry, jnp.argmax(out["total"])
    return jax.lax.scan(step, 0, xs)
bench("scan_nocarry", scan_nocarry, c, stacked)

# 2: vmap over pods (no sequencing)
@jax.jit
def vmapped(c, xs):
    return jax.vmap(lambda p: jnp.argmax(K.schedule_pod(c, p)["total"]))(xs)
bench("vmap", vmapped, c, stacked)

# 3: scan with ONLY the small-resource carry (no pod-row carry)
@jax.jit
def scan_rescarry(c, xs):
    carry0 = {k: c[k] for k in ("requested", "nz_requested", "pod_count")}
    def step(carry, p):
        c2 = dict(c); c2.update(carry)
        out = K.schedule_pod(c2, p)
        best = jnp.argmax(out["total"])
        add = (out["total"][best] >= 0).astype(jnp.int64)
        carry = {
            "requested": carry["requested"].at[best].add(p["req"] * add),
            "nz_requested": carry["nz_requested"].at[best].add(p["nz_req"] * add),
            "pod_count": carry["pod_count"].at[best].add(add.astype(jnp.int32)),
        }
        return carry, best
    return jax.lax.scan(step, carry0, xs)
bench("scan_rescarry", scan_rescarry, c, stacked)

# 4: full carry (current schedule_batch shape)
@jax.jit
def scan_full(c, xs):
    carry0 = {k: c[k] for k in CARRY_KEYS}
    static_c = {k: v for k, v in c.items() if k not in CARRY_KEYS}
    def step(carry, x):
        c2 = dict(static_c); c2.update(carry)
        out = K.schedule_pod(c2, x)
        best = jnp.argmax(out["total"]).astype(jnp.int32)
        feasible = out["total"][best] >= 0
        add = feasible.astype(jnp.int64)
        carry = dict(carry)
        carry["requested"] = carry["requested"].at[best].add(x["req"] * add)
        carry["nz_requested"] = carry["nz_requested"].at[best].add(x["nz_req"] * add)
        carry["pod_count"] = carry["pod_count"].at[best].add(add.astype(jnp.int32))
        pidx = jnp.int32(0)
        carry["pvalid"] = carry["pvalid"].at[pidx].set(feasible)
        carry["ppair"] = carry["ppair"].at[pidx].set(x["self_ppair"])
        carry["pkey"] = carry["pkey"].at[pidx].set(x["self_pkey"])
        carry["pnode"] = carry["pnode"].at[pidx].set(jnp.where(feasible, best, 0))
        carry["pns"] = carry["pns"].at[pidx].set(x["self_ns"])
        carry["pterm"] = carry["pterm"].at[pidx].set(False)
        return carry, best
    return jax.lax.scan(step, carry0, xs)
bench("scan_fullcarry", scan_full, c, stacked)
