"""Is the pallas dispatch's ~580ms fixed cost arg staging or program
complexity? Same signature as the real kernel, trivial body."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax._src.config import enable_x64 as x64ctx

np.asarray(jnp.arange(4) + 1)  # sync mode
Np, VZ, TCp, LANE, SUB, Bp = 5248, 128, 32, 128, 8, 1024

def kernel(breal, tmpl, sc, mf, ms,
           alloc, stat, onehot, regrow, zvnode, zvalid, konnf, konns,
           shasall, validn, rowt, eye, prowf, prows,
           req_in, nzpc_in, cntfn_in, cntsn_in,
           out_ref, req_o, nzpc_o, cntfn_o, cntsn_o):
    req_o[:] = req_in[:]
    nzpc_o[:] = nzpc_in[:]
    cntfn_o[:] = cntfn_in[:]
    cntsn_o[:] = cntsn_in[:]
    out_ref[:] = jnp.full((SUB, Bp), -1, jnp.int32)
    def body(b, _):
        out_ref[:] = out_ref[:] + jnp.int32(1)
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), breal[0], body, jnp.int32(0))

vm = pl.BlockSpec(memory_space=pltpu.VMEM)
sm = pl.BlockSpec(memory_space=pltpu.SMEM)
carr = [jnp.zeros((16, Np), jnp.int32), jnp.zeros((8, Np), jnp.int32),
        jnp.zeros((TCp, Np), jnp.int32), jnp.zeros((TCp, Np), jnp.int32)]
out_shape = (jax.ShapeDtypeStruct((SUB, Bp), jnp.int32),
             *[jax.ShapeDtypeStruct(c.shape, c.dtype) for c in carr])
statics = [jnp.zeros((16, Np), jnp.int32), jnp.zeros((32, Np), jnp.int32),
           jnp.zeros((1, Np, VZ), jnp.float32), jnp.zeros((TCp, Np), jnp.int32),
           jnp.zeros((TCp, Np), jnp.int32), jnp.zeros((TCp, VZ), jnp.int32),
           jnp.zeros((TCp, Np), jnp.int32), jnp.zeros((TCp, Np), jnp.int32),
           jnp.zeros((8, Np), jnp.int32), jnp.zeros((SUB, Np), jnp.int32),
           jnp.zeros((4, TCp, VZ), jnp.int32), jnp.zeros((TCp, LANE), jnp.float32),
           jnp.zeros((TCp, Np), jnp.int32), jnp.zeros((TCp, Np), jnp.int32)]

@jax.jit
def run(carry, breal, tmpl, mf, ms):
    with x64ctx(False):
        return pl.pallas_call(
            kernel, out_shape=out_shape,
            in_specs=[sm, sm, sm, vm, vm] + [vm] * 14 + [vm] * 4,
            out_specs=tuple([vm] * 5),
            input_output_aliases={19 + i: 1 + i for i in range(4)},
        )(breal, tmpl, jnp.zeros(216, jnp.int32), mf, ms, *statics, *carry)

breal = jnp.asarray([Bp], jnp.int32)
tmpl = jnp.zeros(Bp, jnp.int32)
mf = jnp.zeros((Bp, LANE), jnp.int32)
ms = jnp.zeros((Bp, LANE), jnp.int32)
r = run(carr, breal, tmpl, mf, ms)
jax.block_until_ready(r[0])
carr = list(r[1:])
ts = []
for _ in range(4):
    t0 = time.perf_counter()
    r = run(carr, breal, tmpl, mf, ms)
    jax.block_until_ready(r[0])
    carr = list(r[1:])
    ts.append(time.perf_counter() - t0)
print(f"same-signature tiny kernel, {Bp} loop iters: {min(ts)*1e3:.1f}ms")
