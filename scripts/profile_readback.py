import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import CARRY_KEYS, _pack_stacked, _scan_batch_packed
from kubernetes_tpu.ops.kernel import DEFAULT_WEIGHTS
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N, B = 5000, 100
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pods = synth_pending_pods(3*B, spread=True)
for q in pods: pe.encode(q)
c = enc.device_state()
key = tuple(sorted(DEFAULT_WEIGHTS.items()))
static_c = {k: v for k, v in c.items() if k not in CARRY_KEYS}
carry0 = {k: c[k] for k in CARRY_KEYS}

for r in range(3):
    arrays = [{k: v for k, v in pe.encode(q).items() if not k.startswith("_")} for q in pods[r*B:(r+1)*B]]
    t0 = time.perf_counter()
    stacked = {k: np.stack([np.asarray(a[k]) for a in arrays]) for k in arrays[0]}
    packed, layout = _pack_stacked(stacked)
    t1 = time.perf_counter()
    dev = {g: jnp.asarray(a) for g, a in packed.items()}
    pidx = jnp.asarray(np.arange(B, dtype=np.int32))
    valid = jnp.ones(B, bool)
    jax.block_until_ready(dev)
    t2 = time.perf_counter()
    new_carry, ys = _scan_batch_packed(static_c, carry0, dev, pidx, valid, key, layout)
    jax.block_until_ready(ys["best"])
    t3 = time.perf_counter()
    best = np.asarray(ys["best"])
    t4 = time.perf_counter()
    jax.block_until_ready(new_carry)
    t5 = time.perf_counter()
    print(f"r{r}: pack={t1-t0:.3f} upload={t2-t1:.3f} exec(block ys)={t3-t2:.3f} "
          f"readback={t4-t3:.3f} block_carry={t5-t4:.3f} total={t5-t0:.3f}", flush=True)
