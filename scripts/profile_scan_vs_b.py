import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
NB = int(os.environ.get("NPODS", "3072"))
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(NB, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)
arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pending]
templates, seen = [], set()
for a in arrays:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
print("templates:", len(templates), "pod cap:", enc.device_state()["pvalid"].shape)
sess = HoistedSession(enc.device_state(), templates)
for B in (128, 512, 1024):
    def run():
        ys = sess.schedule(arrays[:B])
        jax.block_until_ready(ys["best"])
    run(); run()
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); run(); ts.append(time.perf_counter()-t0)
    print(f"B={B:5d}  {min(ts)*1e3:8.1f}ms  {min(ts)/B*1e3:6.3f} ms/pod")
