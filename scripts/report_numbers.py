"""Print the canonical numbers FROM the committed artifacts.

Every figure quoted in README.md / PERF_NOTES.md must be reproducible by
running this script — prose that contradicts it is a bug (VERDICT r4
weak #3: claims diverging from artifacts). Reads BENCH_CONFIGS.json,
BENCH_WIRE_CONFIGS.json, BENCH_SHARDED.json and the newest BENCH_r*.json.
"""

from __future__ import annotations

import glob
import json
import os
import re

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _rows(path):
    try:
        with open(os.path.join(ROOT, path)) as f:
            return [json.loads(ln) for ln in f if ln.strip()]
    except OSError:
        return []


def _newest_round(rows):
    """{name: that config's newest-round row} (later rows win ties).
    PER CONFIG, not globally newest: a partial-matrix rerun (e.g. the
    round-6 Gang-* staging) must not hide every config it didn't
    re-measure, nor empty the wire-tax intersection below."""
    newest = max((r.get("round", 0) for r in rows), default=0)
    out = {}
    for r in rows:
        prev = out.get(r["name"])
        if prev is None or r.get("round", 0) >= prev.get("round", 0):
            out[r["name"]] = r
    return newest, out


def main() -> None:
    benches = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")),
                     key=lambda p: int(re.findall(r"(\d+)", p)[-1]))
    if benches:
        with open(benches[-1]) as f:
            b = json.load(f)
        p = b.get("parsed", b)
        print(f"kernel-direct ({os.path.basename(benches[-1])}): "
              f"{p.get('value')} pods/s median of {p.get('reps', 1)} reps "
              f"{p.get('rep_pods_per_sec', '')}, warmup {p.get('warmup_compile_s')}s, "
              f"vs 1-core-same-algorithm {p.get('vs_cpu_1core_same_algorithm')}x "
              f"(cpu 1-core {p.get('baseline_cpu_1core_pods_per_sec')} pods/s)")
    for path, label in (("BENCH_CONFIGS.json", "in-proc"),
                        ("BENCH_WIRE_CONFIGS.json", "wire")):
        rows = _rows(path)
        rnd, by_name = _newest_round(rows)
        print(f"\n-- {label} full-loop matrix ({path}, round {rnd}, "
              f"{len(by_name)} configs) --")
        for name in sorted(by_name):
            r = by_name[name]
            key = "attempts_per_sec" if r.get("headline_metric") == \
                "attempts_per_sec" or r.get("saturating") else "throughput_avg"
            print(f"  {name}: {r['throughput_avg']} pods/s avg "
                  f"(p50 {r['throughput_p50']}, attempts/s "
                  f"{r.get('attempts_per_sec')}, attempt_p50 "
                  f"{r.get('attempt_p50')}, reps {r.get('reps')}, "
                  f"runs {r.get('throughput_avg_runs')})")
            if r.get("gang_admitted"):
                print(f"    gangs: admitted {r.get('gang_admitted_runs')}, "
                      f"rollbacks {r.get('gang_rollbacks_runs')}, "
                      f"admission p50 {r.get('gang_admission_p50')}s / "
                      f"p99 {r.get('gang_admission_p99')}s "
                      f"(p99 runs {r.get('gang_admission_p99_runs')})")
    rows = _rows("BENCH_SHARDED.json")
    if rows:
        print("\n-- sharded session (BENCH_SHARDED.json) --")
        for r in rows:
            print(f"  [{r['platform']}] {r['session']} @{r['nodes']}n: "
                  f"{r['pods_per_sec_median']} pods/s median "
                  f"(runs {r['pods_per_sec_runs']})")
    # wire tax from matching configs
    inp = _newest_round(_rows("BENCH_CONFIGS.json"))[1]
    wire = _newest_round(_rows("BENCH_WIRE_CONFIGS.json"))[1]
    common = sorted(set(inp) & set(wire))
    if common:
        print("\n-- wire tax (same config, in-proc vs wire) --")
        for name in common:
            a, b = inp[name]["throughput_avg"], wire[name]["throughput_avg"]
            if a:
                print(f"  {name}: {a} -> {b} pods/s "
                      f"({100 * (a - b) / a:.1f}% tax)")


if __name__ == "__main__":
    main()
