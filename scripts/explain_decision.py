"""Render a pod's scheduling decision as the oracle would log it.

Input is a self-contained cluster file: either a shadow-drift repro
bundle written by the parity sentinel (scheduler/explain.py
write_bundle) or any JSON with ``pod`` / ``nodes`` / ``clusterPods`` in
serde dict form. The CLI replays the decision through the requested
path and prints the per-plugin attribution: which plugin filtered each
rejected node, and the weighted score split of the winner vs the
runners-up.

    JAX_PLATFORMS=cpu python scripts/explain_decision.py BUNDLE.json
    python scripts/explain_decision.py BUNDLE.json --source oracle --top 5
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.api.types import pod_key  # noqa: E402
from kubernetes_tpu.scheduler import explain  # noqa: E402
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", help="repro bundle or pod/nodes/clusterPods JSON")
    ap.add_argument("--source", choices=("device", "oracle"), default="device",
                    help="which path computes the attribution: the fused "
                         "kernel (standalone dispatch) or the oracle "
                         "filter/score chain (default: device)")
    ap.add_argument("--node", default="",
                    help="render this node as the decision instead of the "
                         "replayed winner (e.g. the bundle's recorded bind)")
    ap.add_argument("--top", type=int, default=3,
                    help="runner-up candidates in the score table")
    args = ap.parse_args()

    b = explain.load_bundle(args.bundle)
    pod, nodes, cluster_pods = b["pod"], b["nodes"], b["clusterPods"]
    if args.source == "oracle":
        snap = Snapshot.from_objects(list(cluster_pods), list(nodes))
        bd = explain.oracle_breakdown(snap, pod)
    else:
        bd = explain.device_breakdown(nodes, cluster_pods, pod,
                                      weights=b.get("weights"))
    node = args.node or b.get("node") or None
    print(explain.render_decision(bd, pod_key(pod), node=node, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
