"""Time individual kernel sections at scale (dev tool)."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops import kernel as K
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pending = synth_pending_pods(1, spread=True)[0]
pe.encode(pending)
c = enc.device_state()
p = {k: v for k, v in pe.encode(pending).items() if not k.startswith("_")}

import jax.numpy as jnp
sections = {
    "filter_basics": lambda c, p: K._filter_basics(c, p),
    "node_match": lambda c, p: K._node_match(c, p),
    "pts_filter": lambda c, p: K._pts_filter(c, p, K._node_match(c, p)),
    "ipa_filter": lambda c, p: K._ipa_filter(c, p),
    "score_balanced+least+image": lambda c, p: (K._score_balanced(c, p), K._score_least(c, p), K._score_image(c, p)),
    "score_taint+nodeaff": lambda c, p: (K._score_taint(c, p, c["valid"]), K._score_node_affinity(c, p, c["valid"])),
    "score_pts": lambda c, p: K._score_pts(c, p, K._node_match(c, p), c["valid"]),
    "score_ipa": lambda c, p: K._score_ipa(c, p, c["valid"]),
    "FULL": lambda c, p: K.schedule_pod(c, p),
}
for name, fn in sections.items():
    jf = jax.jit(fn)
    out = jf(c, p); jax.block_until_ready(out)  # compile
    t0 = time.perf_counter()
    for _ in range(10):
        out = jf(c, p)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / 10
    print(f"{name}: {dt*1000:.2f}ms", flush=True)
