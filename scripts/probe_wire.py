#!/usr/bin/env python3
"""Standalone wire fan-out probe (ISSUE 18): N raw-socket watchers x M
in-proc writers against a fresh HTTPAPIServer, per encoding.

What it asserts (exit 1 on violation):

  * SINGLE SERIALIZE — apiserver_wire_serializations_total advances by
    exactly ONE per event per encoding IN USE, never per watcher: the
    hub's broadcast path serializes once and pushes frame bytes by
    reference. A mixed pass (half binary, half JSON watchers) must show
    exactly 2 serializations per event, one per encoding.
  * DELIVERY — every watcher received every event (delivery-histogram
    count delta == events x watchers) with zero evictions.
  * NO P99 REGRESSION — the binary pass's windowed delivery p99
    (bucket-delta p99 of apiserver_watch_delivery_seconds) must not
    exceed slack x the JSON pass measured in the same run (the live
    JSON baseline), unless both sit under an absolute floor where the
    comparison is bucket noise.

Usage: python scripts/probe_wire.py [--watchers 100,1000] [--writers 2]
           [--events 200] [--slack 2.0] [--json OUT]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import selectors
import socket
import sys
import threading
import time
from typing import Dict, List, Tuple
from urllib.parse import urlsplit

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from kubernetes_tpu.api import types as v1  # noqa: E402
from kubernetes_tpu.apiserver import APIServer  # noqa: E402
from kubernetes_tpu.apiserver.http import (  # noqa: E402
    HTTPAPIServer,
    MEDIA_BINARY,
)
from kubernetes_tpu.testing.invariants import (  # noqa: E402
    bucket_counts,
    parse_metrics,
    total,
    window_p99,
)
from kubernetes_tpu.utils import configz  # noqa: E402

DELIVERY = "apiserver_watch_delivery_seconds"
FRAMES = "apiserver_wire_frames_total"
# below this absolute p99 the binary-vs-json comparison is bucket noise
# on the 1-core box, not a regression signal
P99_FLOOR_S = 0.05


def _make_pod(name: str) -> v1.Pod:
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=v1.PodSpec(containers=[v1.Container(
            name="c", resources=v1.ResourceRequirements(
                requests={"cpu": "10m"}))]),
    )


def _snapshot() -> Dict[str, float]:
    return parse_metrics(configz.metricsz_body())


def _delivered(reading: Dict[str, float]) -> float:
    return bucket_counts(reading, DELIVERY).get(float("inf"), 0.0)


def _frames(reading: Dict[str, float]) -> float:
    # one frame per event per sink, counted at push time across encodings
    return total(reading, FRAMES)


class _Drainer:
    """One selector thread draining every watcher socket (1-core box:
    one poll loop beats a thread per socket on the CLIENT side; the
    server side is the thread-per-watcher under test)."""

    def __init__(self) -> None:
        self.sel = selectors.DefaultSelector()
        self.bytes_rx = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="probe-drainer")
        self._t.start()

    def add(self, sock: socket.socket) -> None:
        sock.setblocking(False)
        self.sel.register(sock, selectors.EVENT_READ)

    def _run(self) -> None:
        while not self._stop.is_set():
            for key, _ in self.sel.select(timeout=0.2):
                try:
                    data = key.fileobj.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    data = b""
                if not data:
                    try:
                        self.sel.unregister(key.fileobj)
                    except (KeyError, ValueError):
                        pass
                    continue
                self.bytes_rx += len(data)

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=5)
        for key in list(self.sel.get_map().values()):
            try:
                key.fileobj.close()
            except OSError:
                pass
        self.sel.close()


def _attach_watchers(
    address: str, n: int, binary: bool, drainer: _Drainer,
) -> List[socket.socket]:
    split = urlsplit(address)
    accept = f"Accept: {MEDIA_BINARY}\r\n" if binary else ""
    req = ("GET /api/v1/namespaces/default/pods?watch=true HTTP/1.1\r\n"
           f"Host: {split.hostname}\r\n{accept}\r\n").encode()
    socks = []
    for _ in range(n):
        s = socket.create_connection((split.hostname, split.port),
                                     timeout=10)
        s.sendall(req)
        drainer.add(s)
        socks.append(s)
    return socks


def run_pass(
    watchers: int,
    writers: int,
    events: int,
    mixed: bool = False,
    binary: bool = False,
    n_pods: int = 32,
    timeout: float = 180.0,
) -> dict:
    """One encoding pass: fresh server, attach, write, drain, measure.
    Returns the row dict; raises AssertionError on a contract breach."""
    server = HTTPAPIServer(APIServer())
    server.start()
    drainer = _Drainer()
    encodings = (("binary", "json") if mixed
                 else (("binary",) if binary else ("json",)))
    label = "+".join(encodings)
    try:
        api = server.api
        pods = [api.create("pods", _make_pod(f"w{i}"))
                for i in range(n_pods)]
        if mixed:
            _attach_watchers(server.address, watchers // 2, True, drainer)
            _attach_watchers(server.address, watchers - watchers // 2,
                             False, drainer)
        else:
            _attach_watchers(server.address, watchers, binary, drainer)
        deadline = time.monotonic() + timeout
        while server.watcher_count < watchers:
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"only {server.watcher_count}/{watchers} watchers "
                    "attached before timeout")
            time.sleep(0.02)

        before = _snapshot()
        quotas = [events // writers + (1 if k < events % writers else 0)
                  for k in range(writers)]

        def _writer(k: int) -> None:
            mine = pods[k::writers] or pods
            cur = list(mine)
            for i in range(quotas[k]):
                pod = cur[i % len(cur)]
                pod.metadata.annotations = {"seq": f"{k}.{i}"}
                cur[i % len(cur)] = api.update("pods", pod)

        t0 = time.monotonic()
        ws = [threading.Thread(target=_writer, args=(k,), daemon=True)
              for k in range(writers)]
        for t in ws:
            t.start()
        for t in ws:
            t.join(timeout=timeout)

        # one frame per event per sink, counted at push time; the burst
        # coalescer may fold many events into one socket write, so the
        # delivery HISTOGRAM counts batches — frames are the exact unit
        want_frames = _frames(before) + events * watchers
        while _frames(_snapshot()) < want_frames:
            if time.monotonic() > deadline:
                got = _frames(_snapshot()) - _frames(before)
                raise AssertionError(
                    f"[{label} {watchers}w] pushed {got:.0f}"
                    f"/{events * watchers} frames before timeout")
            time.sleep(0.05)
        wall = time.monotonic() - t0
        # pushed != flushed: wait until every sink buffer is drained and
        # the delivery histogram (observed AFTER the chunked flush) has
        # stopped moving — heartbeats keep raw sockets busy forever, so
        # byte-quiescence is not a usable signal
        quiet, seen = 0, -1.0
        while quiet < 2:
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"[{label} {watchers}w] delivery never quiesced")
            time.sleep(0.15)
            snap = _snapshot()
            depth = total(snap, "apiserver_watch_buffer_depth")
            done = _delivered(snap)
            quiet = quiet + 1 if (depth == 0 and done == seen) else 0
            seen = done
        after = _snapshot()

        ev_delta = total(after, "apiserver_wire_events_total") - \
            total(before, "apiserver_wire_events_total")
        assert ev_delta == events, (
            f"[{label}] wire_events moved {ev_delta}, wrote {events}")
        spe: Dict[str, float] = {}
        for enc in ("binary", "json"):
            key = f'apiserver_wire_serializations_total{{encoding="{enc}"}}'
            delta = after.get(key, 0.0) - before.get(key, 0.0)
            spe[enc] = delta / events
            want = 1.0 if enc in encodings else 0.0
            assert spe[enc] == want, (
                f"[{label} {watchers}w] {delta:.0f} {enc} serializations "
                f"for {events} events — {spe[enc]:.3f}/event, want {want:.0f}"
                " (per-encoding, never per-watcher)")
        evict = total(after, "apiserver_watch_evictions_total") - \
            total(before, "apiserver_watch_evictions_total")
        assert evict == 0, f"[{label} {watchers}w] {evict:.0f} evictions"

        frames = sum(
            after.get(k, 0.0) - before.get(k, 0.0)
            for k in after
            if k.startswith("apiserver_wire_frames_total"))
        enc_bytes = sum(
            after.get(k, 0.0) - before.get(k, 0.0)
            for k in after
            if k.startswith("apiserver_wire_encode_bytes_total"))
        return {
            "name": f"WireFanout-probe-{watchers}w-{label}",
            "watchers": watchers,
            "writers": writers,
            "events": events,
            "encodings": list(encodings),
            "delivery_p99_s": window_p99(before, after, DELIVERY),
            "frames_per_sec": frames / wall if wall > 0 else 0.0,
            "frames": frames,
            "serializations_per_event": sum(spe.values()),
            "encode_bytes": enc_bytes,
            "bytes_rx": drainer.bytes_rx,
            "evictions": evict,
            "wall_s": wall,
        }
    finally:
        drainer.stop()
        server.stop()


def run_probe(
    watcher_counts: List[int],
    writers: int,
    events: int,
    slack: float,
    timeout: float = 180.0,
) -> Tuple[List[dict], List[str]]:
    rows: List[dict] = []
    failures: List[str] = []
    for n in watcher_counts:
        try:
            base = run_pass(n, writers, events, binary=False,
                            timeout=timeout)
            rows.append(base)
            binr = run_pass(n, writers, events, binary=True,
                            timeout=timeout)
            rows.append(binr)
            p99_j, p99_b = base["delivery_p99_s"], binr["delivery_p99_s"]
            if (p99_b > slack * p99_j and p99_b > P99_FLOOR_S
                    and math.isfinite(p99_b)):
                failures.append(
                    f"{n}w: binary delivery p99 {p99_b:.4f}s regressed "
                    f"past {slack:.1f}x the JSON baseline {p99_j:.4f}s")
        except AssertionError as e:
            failures.append(str(e))
    # one mixed pass at the smallest scale: encodings-count semantics
    try:
        n = min(watcher_counts)
        mixed = run_pass(max(2, n), writers, events, mixed=True,
                         timeout=timeout)
        rows.append(mixed)
        if mixed["serializations_per_event"] != 2.0:
            failures.append(
                f"mixed pass: {mixed['serializations_per_event']:.3f} "
                "serializations/event, want exactly 2 (one per encoding)")
    except AssertionError as e:
        failures.append(str(e))
    return rows, failures


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--watchers", default="100,1000",
                    help="comma-separated watcher counts")
    ap.add_argument("--writers", type=int, default=2)
    ap.add_argument("--events", type=int, default=200)
    ap.add_argument("--slack", type=float, default=2.0,
                    help="binary p99 must stay within slack x JSON p99")
    ap.add_argument("--timeout", type=float, default=180.0)
    ap.add_argument("--json", default="",
                    help="also write rows as JSON lines to this path")
    args = ap.parse_args(argv)
    counts = [int(x) for x in args.watchers.split(",") if x]

    rows, failures = run_probe(counts, args.writers, args.events,
                               args.slack, timeout=args.timeout)
    for r in rows:
        print(f"{r['name']:40s} p99={r['delivery_p99_s'] * 1e3:8.2f}ms "
              f"frames/s={r['frames_per_sec']:10.0f} "
              f"ser/event={r['serializations_per_event']:.2f} "
              f"rx={r['bytes_rx'] / 1e6:7.1f}MB wall={r['wall_s']:.2f}s")
    if args.json:
        with open(args.json, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
    if failures:
        print("\nPROBE FAILURES:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("\nwire probe OK: single-serialize held, no p99 regression")
    return 0


if __name__ == "__main__":
    sys.exit(main())
