"""Full scheduler_perf-style benchmark suite (one JSON line per workload).

Mirrors the reference's performance-config.yaml coverage at configurable
scale: SchedulingBasic, PodTopologySpread (preferred zone spread + hard
hostname spread), required PodAntiAffinity on hostname, and the
gang-scheduling stress (8-pod groups with extended GPU resources).
bench.py remains the single-number headline; this is the coverage sweep
(reference: test/integration/scheduler_perf/config/
performance-config.yaml, scheduler_perf_test.go).

  python scripts/benchmarks.py              # small CI shapes
  BENCH_SCALE=full python scripts/benchmarks.py   # 5000-node shapes
"""
import json
import os
import sys

os.environ.setdefault("JAX_ENABLE_X64", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
# honor JAX_PLATFORMS=cpu even where a TPU plugin force-prepends itself
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from kubernetes_tpu.perf import Workload, run_workload  # noqa: E402
from kubernetes_tpu.perf.harness import PodTemplate  # noqa: E402

FULL = os.environ.get("BENCH_SCALE") == "full"
NODES = 5000 if FULL else 200
INIT = 1000 if FULL else 100
PODS = 1000 if FULL else 200
BACKEND = os.environ.get("BENCH_BACKEND", "tpu")

WORKLOADS = [
    Workload(
        name="SchedulingBasic",
        num_nodes=NODES, num_init_pods=INIT, num_pods=PODS,
        backend=BACKEND,
    ),
    Workload(
        name="SchedulingPodTopologySpread",
        num_nodes=NODES, num_init_pods=INIT, num_pods=PODS,
        template=PodTemplate(spread_zone=True),
        backend=BACKEND,
    ),
    Workload(
        name="SchedulingPreferredPodTopologySpread",
        num_nodes=NODES, num_init_pods=INIT, num_pods=PODS,
        init_template=PodTemplate(spread_zone=True),
        template=PodTemplate(spread_zone=True),
        backend=BACKEND,
    ),
    Workload(
        name="SchedulingPodAntiAffinity",
        num_nodes=NODES, num_init_pods=0,
        # hostname anti-affinity: one pod per node max, so NODES//2
        # measured pods stay well inside feasibility
        num_pods=min(PODS, NODES // 2),
        template=PodTemplate(anti_affinity_hostname=True),
        backend=BACKEND,
    ),
    Workload(
        name="SchedulingHardHostnameSpread",
        num_nodes=NODES, num_init_pods=0, num_pods=min(PODS, NODES // 2),
        template=PodTemplate(spread_hostname_hard=True),
        backend=BACKEND,
    ),
    Workload(
        name="SchedulingGangStress",
        num_nodes=NODES, num_init_pods=0, num_pods=min(PODS, 512),
        gang_size=8,
        template=PodTemplate(extended={"example.com/gpu": "1"}),
        node_extended={"example.com/gpu": "8"},
        backend=BACKEND,
    ),
]

for w in WORKLOADS:
    try:
        result = run_workload(w)
        print(json.dumps(result.to_dict()), flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"name": w.name, "error": str(e)}), flush=True)
