"""Single-core CPU baseline of the SAME dense scheduling math.

Run by bench.py in a subprocess pinned to one CPU core (taskset -c 0)
with JAX_PLATFORMS=cpu: the identical hoisted-session program (same
cluster arrays, same sequential-assume scan, same decisions) compiled by
XLA for one CPU thread. This is the honest same-algorithm CPU
denominator BASELINE.md's north star asks for ("single-goroutine CPU
baseline with identical decisions") — stronger than a hand-written
numpy twin, because it is literally the same program, and conservative,
because XLA-CPU is faster than numpy.

Prints one JSON line: {"pods_per_sec": ..., "n_pods": ..., "n_nodes": ...}.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")
# one intra-op thread: the baseline must stay single-core even if the
# taskset pin is unavailable
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_cpu_multi_thread_eigen=false intra_op_parallelism_threads=1"
).strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.utils.compilation_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()


def main() -> None:
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    n_meas = int(os.environ.get("BENCH_CPU_PODS", "256"))
    batch = int(os.environ.get("BENCH_CPU_BATCH", "256"))

    from kubernetes_tpu.models.encoding import ClusterEncoding
    from kubernetes_tpu.ops.hoisted import HoistedSession
    from kubernetes_tpu.models.pod_encoder import PodEncoder
    from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

    nodes, init_pods = synth_cluster(n_nodes, pods_per_node=2)
    pending = synth_pending_pods(batch + n_meas, spread=True)

    enc = ClusterEncoding()
    for node in nodes:
        enc.add_node(node)
    for pod in init_pods:
        enc.add_pod(pod, pod.spec.node_name)
    pe = PodEncoder(enc)
    arrays = [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
        for p in pending
    ]
    from kubernetes_tpu.ops.hoisted import template_fingerprint

    cluster = enc.device_state()
    templates: dict = {}
    for a in arrays:
        templates.setdefault(template_fingerprint(a), a)
    session = HoistedSession(cluster, list(templates.values()), weights=None)
    # warmup batch: compile + prologue outside the measured window
    ys = session.schedule(arrays[:batch])
    HoistedSession.decisions(ys)
    t0 = time.perf_counter()
    done = 0
    while done < n_meas:
        chunk = arrays[batch + done: batch + done + batch]
        ys = session.schedule(chunk)
        HoistedSession.decisions(ys)  # blocks: decisions on host
        done += len(chunk)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "pods_per_sec": round(n_meas / dt, 3),
        "n_pods": n_meas,
        "n_nodes": n_nodes,
        "note": (
            "identical hoisted-session program on ONE CPU core "
            "(taskset + single-thread XLA): same arrays, same "
            "sequential-assume scan, same decisions"
        ),
    }))


if __name__ == "__main__":
    main()
