import os, sys, subprocess
base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for skip in ("", "ptsf", "ptss", "zp", "updates", "ptsf,ptss,zp", "ptsf,ptss,zp,updates"):
    env = dict(os.environ, KTPU_PALLAS_SKIP=skip, BENCH_BATCH="512")
    r = subprocess.run([sys.executable, os.path.join(base, "scripts", "profile_pallas.py")],
                       env=env, capture_output=True, text=True, timeout=1500)
    line = [l for l in r.stdout.split("\n") if "steady" in l]
    print(f"skip={skip or '<none>':24s} {line[0] if line else 'FAILED: ' + r.stderr.strip()[-120:]}")
