"""Probe: what-if preemption-launch cost vs candidate count and
eviction depth (ISSUE 7 tooling satellite).

Builds saturated clusters directly against a TPU backend (no apiserver —
this measures the planner, not the loop) and, for each (nodes,
victims-per-node) point, plans a preemptor wave three ways:

  * device — DevicePreemptionPlanner: one fused what-if launch per
             preemptor (base feasibility + the full reprieve walk over
             every candidate node);
  * fast   — the numpy FastPreemptionPlanner (the pre-PR-7 best case,
             resource-fit envelope only);
  * oracle — the DefaultPreemption plugin dry-run (the per-candidate
             filter-chain walk the device rung replaces).

Every point PARITY-ASSERTS the three planners (node choice + victim
sets) before reporting timings, and a second sweep runs an
affinity-carrying preemptor (outside the numpy envelope) device-vs-
oracle only. Reports per-preemptor plan cost and the implied speedup.

CPU-runnable as-is (the what-if program runs through the hoisted-view
scratch context); on a TPU the same script probes real launch cost:

    JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python scripts/probe_preemption.py

Exit is nonzero on any parity divergence.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.api import types as v1  # noqa: E402
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot  # noqa: E402
from kubernetes_tpu.scheduler.internal.nominator import PodNominator  # noqa: E402
from kubernetes_tpu.scheduler.preemption import (  # noqa: E402
    FastPreemptionPlanner,
)
from kubernetes_tpu.scheduler.preemption_device import (  # noqa: E402
    DevicePreemptionPlanner,
)
from kubernetes_tpu.scheduler.tpu_backend import TPUBackend  # noqa: E402
from kubernetes_tpu.testing.synth import make_node, make_pod  # noqa: E402


def saturated_cluster(n_nodes: int, victims_per_node: int,
                      labels=None, zones: int = 3):
    cpu_m = 4000 // max(victims_per_node + 1, 1)
    nodes = [
        make_node(f"n{i}", cpu="4", pods=2 * victims_per_node + 4,
                  labels={"zone": f"z{i % zones}",
                          v1.LABEL_HOSTNAME: f"n{i}"})
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_nodes):
        for j in range(victims_per_node):
            p = make_pod(
                f"low-{i}-{j}", cpu=f"{cpu_m}m", memory="64Mi",
                node_name=f"n{i}", priority=1, labels=labels or {},
            )
            p.status.start_time = float((i * 31 + j * 7) % 97)
            pods.append(p)
    return nodes, pods, cpu_m


def mk_backend(nodes, pods):
    b = TPUBackend()
    b.whatif = True  # CPU platform default is off; the probe opts in
    for n in nodes:
        b.on_add_node(n)
    for p in pods:
        b.on_add_pod(p, p.spec.node_name)
    return b


def oracle_plan(snapshot, pending, pdbs=()):
    from tests.test_preemption import _post_filter  # noqa: E402

    result, _ = _post_filter(snapshot, pending, pdbs=list(pdbs))
    if result is None:
        return None
    return (result.nominated_node_name,
            sorted(p.metadata.name for p in result.victims))


def cand_key(c):
    from kubernetes_tpu.scheduler.preemption_device import ORACLE_FALLBACK

    if c is None:
        return None
    if c is ORACLE_FALLBACK:  # device rung failed; report, don't crash
        return "oracle-fallback"
    return (c.node_name, sorted(p.metadata.name for p in c.victims))


def time_wave(plan_fn, reps: int):
    # warm (compiles) then measure
    plan_fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        out = plan_fn()
    return (time.perf_counter() - t0) / reps, out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", default="50x2,200x4,500x4,500x8",
                    help="comma list of <nodes>x<victims-per-node>")
    ap.add_argument("--wave", type=int, default=8,
                    help="preemptors per wave")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--oracle-cap", type=int, default=200,
                    help="skip the oracle timing above this node count "
                         "(it is the slow thing being replaced)")
    args = ap.parse_args()
    platform = jax.devices()[0].platform
    print(f"platform={platform} wave={args.wave} reps={args.reps}",
          file=sys.stderr)
    diverged = 0
    rows = []
    for point in args.points.split(","):
        n_nodes, vpn = (int(x) for x in point.strip().split("x"))
        nodes, pods, cpu_m = saturated_cluster(n_nodes, vpn)
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = mk_backend(nodes, pods)
        wave = [
            make_pod(f"hi-{k}", cpu=f"{cpu_m}m", memory="64Mi",
                     priority=100)
            for k in range(args.wave)
        ]
        elig = {v1.pod_key(p): (True, True) for p in wave}

        def dev_plan():
            pl = DevicePreemptionPlanner(
                snapshot, PodNominator(), backend, eligibility=elig)
            return pl.plan(list(wave))

        def fast_plan():
            pl = FastPreemptionPlanner(snapshot, PodNominator())
            return pl.plan(list(wave))

        dt_dev, dev_out = time_wave(dev_plan, args.reps)
        dt_fast, fast_out = time_wave(fast_plan, args.reps)
        if [cand_key(c) for c in dev_out] != \
                [cand_key(c) for c in fast_out]:
            print(f"!! {point}: device vs fast DIVERGED", file=sys.stderr)
            diverged += 1
        dt_oracle = None
        if n_nodes <= args.oracle_cap:
            t0 = time.perf_counter()
            ok = oracle_plan(snapshot, wave[0])
            dt_oracle = time.perf_counter() - t0
            if cand_key(dev_out[0]) != ok:
                print(f"!! {point}: device vs oracle DIVERGED",
                      file=sys.stderr)
                diverged += 1
        row = {
            "point": point, "nodes": n_nodes, "victims_per_node": vpn,
            "wave": args.wave, "platform": platform,
            "device_ms_per_preemptor": round(
                1e3 * dt_dev / args.wave, 3),
            "fast_ms_per_preemptor": round(1e3 * dt_fast / args.wave, 3),
            "oracle_ms_per_preemptor": (
                round(1e3 * dt_oracle, 3) if dt_oracle is not None
                else None),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    # affinity-carrying preemptors: outside the numpy envelope —
    # device vs oracle only (the capability extension)
    for point in ("50x2", "200x4"):
        n_nodes, vpn = (int(x) for x in point.split("x"))
        nodes, pods, cpu_m = saturated_cluster(
            n_nodes, vpn, labels={"app": "victim"})
        snapshot = Snapshot.from_objects(pods, nodes)
        backend = mk_backend(nodes, pods)
        aff = v1.Affinity(pod_affinity=v1.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                v1.PodAffinityTerm(
                    label_selector=v1.LabelSelector(
                        match_labels={"app": "victim"}),
                    topology_key="zone",
                )
            ]
        ))
        wave = [
            make_pod(f"ahi-{k}", cpu=f"{cpu_m}m", memory="64Mi",
                     priority=100, labels={"app": "victim"},
                     affinity=aff)
            for k in range(args.wave)
        ]
        elig = {v1.pod_key(p): (True, False) for p in wave}

        def dev_plan():
            pl = DevicePreemptionPlanner(
                snapshot, PodNominator(), backend, eligibility=elig)
            return pl.plan(list(wave))

        dt_dev, dev_out = time_wave(dev_plan, args.reps)
        dt_oracle = None
        if n_nodes <= args.oracle_cap:
            t0 = time.perf_counter()
            ok = oracle_plan(snapshot, wave[0])
            dt_oracle = time.perf_counter() - t0
            if cand_key(dev_out[0]) != ok:
                print(f"!! affinity {point}: device vs oracle DIVERGED",
                      file=sys.stderr)
                diverged += 1
        row = {
            "point": point, "profile": "ipa-affinity",
            "nodes": n_nodes, "victims_per_node": vpn,
            "wave": args.wave, "platform": platform,
            "device_ms_per_preemptor": round(
                1e3 * dt_dev / args.wave, 3),
            "oracle_ms_per_preemptor": (
                round(1e3 * dt_oracle, 3) if dt_oracle is not None
                else None),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    if diverged:
        print(f"{diverged} parity divergences", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
