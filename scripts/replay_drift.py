"""Replay a shadow parity drift bundle and report whether it reproduces.

Loads a repro bundle written by the parity sentinel (scheduler/
explain.py write_bundle: decision-time cluster objects + pod + the
device's score weights), re-runs BOTH paths from scratch — the device
explain path (fused kernel, standalone dispatch) and the oracle
filter/score chain — and prints the per-plugin diff table at the
decision node. Exits nonzero iff the drift reproduces from the frozen
state; exit 0 means the frozen objects agree (the original drift was
transient: an informer race, a since-fixed kernel, a corrupted session).

    JAX_PLATFORMS=cpu python scripts/replay_drift.py \
        /tmp/ktpu-shadow-bundles/shadow-drift-default-web-1-*.json
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from kubernetes_tpu.api.types import pod_key  # noqa: E402
from kubernetes_tpu.scheduler import explain  # noqa: E402
from kubernetes_tpu.scheduler.framework.snapshot import Snapshot  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", help="shadow-drift repro bundle (JSON)")
    ap.add_argument("--top", type=int, default=3,
                    help="runner-up candidates in the rendered decision")
    args = ap.parse_args()

    b = explain.load_bundle(args.bundle)
    pod, nodes, cluster_pods = b["pod"], b["nodes"], b["clusterPods"]
    key = pod_key(pod)
    print(f"bundle: {args.bundle}")
    print(f"  recorded: node={b.get('node')} plugins={b.get('plugins')}")

    snap = Snapshot.from_objects(list(cluster_pods), list(nodes))
    oracle_bd = explain.oracle_breakdown(snap, pod)
    device_bd = explain.device_breakdown(nodes, cluster_pods, pod,
                                         weights=b.get("weights"))
    decision = device_bd.get("decision")

    drifted = explain.decision_drifts(oracle_bd, decision)
    plugins = explain.attribution_diff(oracle_bd, device_bd)
    if drifted and not plugins:
        plugins = explain.drift_plugins(oracle_bd, device_bd, decision)

    print()
    print("device replay:")
    print(explain.render_decision(device_bd, key, node=decision, top=args.top))
    print()
    print("oracle replay:")
    print(explain.render_decision(oracle_bd, key, top=args.top))
    print()
    at = decision or (oracle_bd["best"][0] if oracle_bd["best"] else None)
    if at is not None:
        print(explain.diff_table(oracle_bd, device_bd, at))
        print()
    if drifted or plugins:
        print(f"DRIFT REPRODUCES: pod {key} "
              f"(device={decision}, oracle best={oracle_bd['best']}, "
              f"plugins: {', '.join(plugins) or 'decision'})")
        return 1
    print(f"no drift: device and oracle agree on the frozen objects "
          f"(decision={decision})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
