"""Per-step vs fixed cost of the batched scan: time B in {1,8,32,128}.

Slope = true per-step device cost; intercept = dispatch/tunnel overhead.
Inputs are re-uploaded fresh each run (new arrays) to defeat any
tunnel-side execution/result caching.
"""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops import batch as B
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(300, spread=True)
enc = ClusterEncoding()
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"phantom-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]:
    pe.encode(p)
enc.device_state()
for q in phantoms:
    enc.remove_pod(q)

print("device:", jax.devices()[0])
for bs in (1, 8, 32, 128):
    pods = pending[:bs]
    arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pods]
    c = enc.device_state()
    slots = [enc._pod_free[-1 - i] for i in range(bs)]
    # warm compile
    d, _ = B.schedule_batch(c, arrays, slots)
    times = []
    for rep in range(3):
        t0 = time.perf_counter()
        d, carry = B.schedule_batch(c, arrays, slots)
        jax.block_until_ready(carry)
        times.append(time.perf_counter() - t0)
    print(f"B={bs:4d}  best={min(times)*1e3:8.1f}ms  per-step={min(times)/bs*1e3:7.2f}ms  times={[f'{t*1e3:.0f}' for t in times]}")
