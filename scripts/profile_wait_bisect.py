import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = 5000
B = 1024
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(5 * B, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)

def encode_batch(pods):
    return [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pods]

all_arrays = [encode_batch(pending[i*B:(i+1)*B]) for i in range(5)]
templates, seen = [], set()
for a in all_arrays[0]:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
sess = HoistedSession(enc.device_state(), templates)
ys = sess.schedule(all_arrays[0]); dec0 = HoistedSession.decisions(ys)  # warm

def timed(tag, arrays, harvest_pods=None, reencode=False):
    if reencode:
        t0 = time.perf_counter(); arrays = encode_batch(reencode); t = time.perf_counter()-t0
        print(f"  (re-encode {t*1e3:.0f}ms)", end="")
    t0 = time.perf_counter()
    ys = sess.schedule(arrays)
    t_d = time.perf_counter()-t0
    t0 = time.perf_counter()
    dec = HoistedSession.decisions(ys)
    t_w = time.perf_counter()-t0
    print(f" {tag}: dispatch={t_d*1e3:6.1f}ms wait={t_w*1e3:7.1f}ms")
    if harvest_pods is not None:
        t0 = time.perf_counter()
        for p, b in zip(harvest_pods, dec):
            if b >= 0: enc.add_pod(p, enc.node_names[b])
        print(f"   harvest={1e3*(time.perf_counter()-t0):.0f}ms")
    return dec

# 1: plain repeat (pre-encoded, no harvest)
timed("pre-encoded, no harvest", all_arrays[1])
# 2: pre-encoded + harvest of batch 1's pods
timed("pre-encoded, harvest prev", all_arrays[2], harvest_pods=pending[2*B:3*B])
# 3: after harvest, schedule pre-encoded again
timed("pre-encoded, after harvest", all_arrays[3])
# 4: re-encode then schedule
timed("re-encoded", None, reencode=pending[4*B:5*B])
