"""Pallas viability probe on the tunnel TPU: (1) sequential-grid scan
with VMEM scratch carry — per-step cost in sync mode; (2) int64 inside
a kernel."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# honest mode
p = jnp.arange(4) + 1; jax.block_until_ready(p); np.asarray(p)

N = 5120  # padded node axis
B = 512

def kernel(req_ref, alloc_ref, out_ref, util_ref):
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _init():
        util_ref[:] = jnp.zeros_like(util_ref)

    req = req_ref[b, 0]
    util = util_ref[0, :]
    fits = util + req <= alloc_ref[0, :]
    score = jnp.where(fits, alloc_ref[0, :] - util, -1.0)
    best = jax.lax.argmax(score, 0, jnp.int32)
    # one-hot vector accumulate (scalar scatters to VMEM are unsupported)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)[0]
    util_ref[0, :] = util + jnp.where(lane == best, req, 0.0)
    out_ref[b, :] = jnp.full((128,), best, jnp.int32)

@jax.jit
def run(req, alloc):
    return pl.pallas_call(
        kernel,
        grid=(B,),
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((1, N), jnp.float32)],
    )(req, alloc)

req = jnp.ones((B, 1), jnp.float32) * 0.5
alloc = jnp.ones((1, N), jnp.float32) * 3.0
out = run(req, alloc)
jax.block_until_ready(out)
ts = []
for _ in range(3):
    t0 = time.perf_counter()
    jax.block_until_ready(run(req, alloc))
    ts.append(time.perf_counter() - t0)
o = np.asarray(out)[:, 0]
print(f"pallas scan B={B}: {min(ts)*1e3:.1f}ms ({min(ts)/B*1e6:.1f} us/pod); "
      f"first 8 decisions: {o[:8]}")
# each node fits 6 pods of 0.5 in 3.0: decisions should rotate as nodes fill
assert len(set(o.tolist())) > 1 or B <= 6

# int64 probe
def k64(a_ref, o_ref):
    o_ref[:] = a_ref[:] * 2 + 1

try:
    a = jnp.arange(8 * 128, dtype=jnp.int64).reshape(8, 128)
    r = pl.pallas_call(
        k64,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int64),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(a)
    print("int64 in pallas: OK", np.asarray(r)[0, :3])
except Exception as e:
    print("int64 in pallas FAILED:", type(e).__name__, str(e)[:200])
