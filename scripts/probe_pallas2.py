import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, B = 5120, 64

def try_kernel(name, kernel, out_shape, scratch):
    try:
        f = pl.pallas_call(
            kernel, grid=(B,), out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
        )
        req = jnp.ones((B, 128), jnp.float32) * 0.5
        alloc = jnp.ones((1, N), jnp.float32) * 3.0
        r = jax.block_until_ready(f(req, alloc))
        print(f"{name}: OK")
        return r
    except Exception as e:
        msg = str(e)
        for line in msg.split("\n"):
            if "legalize" in line or "NotImplemented" in line or "Mosaic" in line:
                msg = line.strip(); break
        print(f"{name}: FAIL {type(e).__name__}: {msg[:140]}")
        return None

# 1: grid + scratch init + plain vector write
def k1(req_ref, alloc_ref, out_ref, util_ref):
    b = pl.program_id(0)
    @pl.when(b == 0)
    def _():
        util_ref[:] = jnp.zeros_like(util_ref)
    out_ref[pl.ds(b, 1), :] = req_ref[pl.ds(b, 1), :] + util_ref[0, 0]
try_kernel("k1 grid+scratch+dswrite", k1,
           jax.ShapeDtypeStruct((B, 128), jnp.float32),
           [pltpu.VMEM((1, N), jnp.float32)])

# 2: + argmax int32
def k2(req_ref, alloc_ref, out_ref, util_ref):
    b = pl.program_id(0)
    @pl.when(b == 0)
    def _():
        util_ref[:] = jnp.zeros_like(util_ref)
    score = alloc_ref[0, :] - util_ref[0, :]
    best = jax.lax.argmax(score, 0, jnp.int32)
    out_ref[pl.ds(b, 1), :] = jnp.full((1, 128), best, jnp.float32)
try_kernel("k2 +argmax", k2,
           jax.ShapeDtypeStruct((B, 128), jnp.float32),
           [pltpu.VMEM((1, N), jnp.float32)])

# 3: + one-hot scratch update
def k3(req_ref, alloc_ref, out_ref, util_ref):
    b = pl.program_id(0)
    @pl.when(b == 0)
    def _():
        util_ref[:] = jnp.zeros_like(util_ref)
    util = util_ref[0, :]
    score = alloc_ref[0, :] - util
    best = jax.lax.argmax(score, 0, jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    util_ref[:, :] = util[None, :] + jnp.where(lane == best, req_ref[b, 0], 0.0)
    out_ref[pl.ds(b, 1), :] = jnp.full((1, 128), best, jnp.float32)
r = try_kernel("k3 +onehot-update", k3,
               jax.ShapeDtypeStruct((B, 128), jnp.float32),
               [pltpu.VMEM((1, N), jnp.float32)])
if r is not None:
    print("decisions:", np.asarray(r)[:8, 0])
