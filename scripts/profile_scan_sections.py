"""Which kernel section dominates the scan step? Stub sections one at a
time (monkeypatch kernel module globals) and re-time the whole scan."""
import os, sys, time, functools
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops import kernel as K
from kubernetes_tpu.ops.batch import CARRY_KEYS, _step
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N, B = int(os.environ.get("BENCH_NODES", "5000")), 64
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pods = synth_pending_pods(B, spread=True)
for q in pods: pe.encode(q)
c = enc.device_state()
arrays = [{k: v for k, v in pe.encode(q).items() if not k.startswith("_")} for q in pods]
stacked = {k: jnp.asarray(np.stack([np.asarray(a[k]) for a in arrays])) for k in arrays[0]}
slots = np.asarray([enc._pod_free[-1 - i] for i in range(B)], np.int32)
xs = {"pod": stacked, "pidx": jnp.asarray(slots), "valid": jnp.ones(B, bool)}
static_c = {k: v for k, v in c.items() if k not in CARRY_KEYS}
carry = {k: c[k] for k in CARRY_KEYS}

n = int(np.asarray(c["valid"]).shape[0])
ones_n = jnp.ones(n, bool)
zeros_n = jnp.zeros(n, jnp.int64)

STUBS = {
    "pts_filter": ("_pts_filter", lambda c, p, nm: (ones_n, jnp.zeros(n, bool))),
    "ipa_filter": ("_ipa_filter", lambda c, p: (ones_n, jnp.zeros(n, bool))),
    "score_pts": ("_score_pts", lambda c, p, nm, f: zeros_n),
    "score_ipa": ("_score_ipa", lambda c, p, f: zeros_n),
    "node_match": ("_node_match", lambda c, p: ones_n),
    "filter_basics": ("_filter_basics", lambda c, p: (ones_n,) * 5),
    "scores_basic": ("_score_balanced", lambda c, p: zeros_n),
    "score_taint": ("_score_taint", lambda c, p, f: zeros_n),
    "score_nodeaff": ("_score_node_affinity", lambda c, p, f: zeros_n),
    "score_image": ("_score_image", lambda c, p: zeros_n),
}

def run(name):
    @jax.jit
    def jf(carry, xs):
        step = functools.partial(_step, static_c, K.DEFAULT_WEIGHTS)
        return jax.lax.scan(step, carry, xs)
    out = jf(carry, xs); jax.block_until_ready(out)
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        out = jf(carry, xs); jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{name:24s} {best*1000:8.1f}ms  {best*1000/B:6.2f}ms/pod", flush=True)
    return best

print("device:", jax.devices()[0], " B =", B, " N =", n, " P =", np.asarray(c['pvalid']).shape)
full = run("FULL")
for label, (attr, stub) in STUBS.items():
    orig = getattr(K, attr)
    setattr(K, attr, stub)
    try:
        run(f"minus {label}")
    finally:
        setattr(K, attr, orig)
# everything stubbed: pure scan+argmax+carry-update floor
origs = {attr: getattr(K, attr) for attr, _ in STUBS.values()}
for attr, stub in STUBS.values():
    setattr(K, attr, stub)
try:
    run("minus ALL (floor)")
finally:
    for attr, fn in origs.items():
        setattr(K, attr, fn)
