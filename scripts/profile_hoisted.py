"""Hoisted-path cost split: fixed (prologue+dispatch) vs per-step."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import copy
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import schedule_batch_hoisted
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(300, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = copy.deepcopy(p); q.metadata.name = f"ph-{i}"
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)

print("device:", jax.devices()[0])
for bs in (8, 64, 256):
    pods = pending[:bs]
    arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pods]
    c = enc.device_state()
    schedule_batch_hoisted(c, arrays)  # compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        d, ys = schedule_batch_hoisted(c, arrays)
        jax.block_until_ready(ys["best"])
        times.append(time.perf_counter() - t0)
    print(f"B={bs:4d}  best={min(times)*1e3:8.1f}ms  per-step={min(times)/bs*1e3:7.2f}ms")
