"""PallasSession on the real chip at bench scale: compile + honest timing
+ decision parity vs the jnp HoistedSession."""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.ops.pallas_scan import PallasSession
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
B = int(os.environ.get("BENCH_BATCH", "1024"))
M = 3
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(M * B, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)
arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pending]
templates, seen = [], set()
for a in arrays:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
print("templates:", len(templates), "device:", jax.devices()[0])

t0 = time.perf_counter()
# multipod_k=1: this script treats decisions() as final (no
# conflict-suffix replay loop) — profile the one-pod-per-step path
ps = PallasSession(enc.device_state(), templates, multipod_k=1)
print(f"session build (prologue + remap): {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
ys = ps.schedule(arrays[:B])
d0 = PallasSession.decisions(ys)   # also flips to honest sync mode
print(f"first schedule (compile): {time.perf_counter()-t0:.1f}s")
ts = []
outs = [d0]
for i in range(1, M):
    t0 = time.perf_counter()
    ys = ps.schedule(arrays[i*B:(i+1)*B])
    d = PallasSession.decisions(ys)
    ts.append(time.perf_counter() - t0)
    outs.append(d)
print(f"pallas steady: {min(ts)*1e3:.1f}ms/batch ({min(ts)/B*1e6:.1f} us/pod)")

# parity vs jnp session on the same batches
js = HoistedSession(enc.device_state(), templates)
ref = []
for i in range(M):
    ref.append(HoistedSession.decisions(js.schedule(arrays[i*B:(i+1)*B])))
for i in range(M):
    same = outs[i] == ref[i]
    n_diff = sum(1 for a, b in zip(outs[i], ref[i]) if a != b)
    print(f"batch {i}: parity={'OK' if same else f'{n_diff} DIFF'}")
