"""Crash drill: seed a cluster, SIGKILL-equivalently drop the apiserver
mid-churn (plus one supervised controller), recover, and assert that

  * every write acknowledged to a client is present after recovery
    (the durable store's fsync-before-ack contract),
  * informers re-list on their dead watches and re-converge,
  * the crashed controller is restarted by the supervisor with capped
    backoff while the others keep running.

Standalone repro harness for the WAL+snapshot subsystem (store/kv.py
DurableKVStore + controllers/manager.Supervisor + testing/chaos.py crash
disruptions). Runs on CPU:

    JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python scripts/crash_drill.py

JAX_ENABLE_X64=1 is required (score/resource math is int64; the pytest
conftest sets it for the suite, standalone scripts must set it
themselves — this script defaults both vars if unset).
"""

import argparse
import os
import random
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.api import apps, types as v1  # noqa: E402
from kubernetes_tpu.cluster import Cluster  # noqa: E402
from kubernetes_tpu.testing.chaos import ChaosMonkey  # noqa: E402


def wait_until(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def deployment(name: str, replicas: int) -> apps.Deployment:
    return apps.Deployment(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=apps.DeploymentSpec(
            replicas=replicas,
            selector=v1.LabelSelector(match_labels={"app": name}),
            template=apps.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": name}),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(requests={"cpu": "20m"}),
                )]),
            ),
        ),
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--replicas", type=int, default=6)
    ap.add_argument("--writes", type=int, default=60, help="churn writes")
    ap.add_argument("--crashes", type=int, default=3, help="apiserver crashes")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--dir", default=None, help="durable store dir (tmp default)")
    args = ap.parse_args()

    path = args.dir or tempfile.mkdtemp(prefix="crash-drill-")
    rng = random.Random(args.seed)
    failures = []

    with Cluster(
        n_nodes=args.nodes,
        durable_path=path,
        scheduler_backend="oracle",
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
            "supervisor_opts": dict(base_backoff=0.05, probe_period=0.02),
        },
    ) as c:
        c.client.resource("deployments").create(deployment("ha", args.replicas))

        def n_running():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.status.phase == "Running")

        if not wait_until(lambda: n_running() == args.replicas, timeout=60):
            print(f"FAIL: initial convergence ({n_running()}/{args.replicas})")
            return 1
        print(f"seeded: {args.replicas} replicas running on {args.nodes} nodes")

        monkey = ChaosMonkey(
            c, rng=rng, disruptions=["crash-apiserver", "crash-controller"]
        )

        # churn acknowledged writes while crashes land mid-burst
        acked = []
        crash_at = sorted(rng.sample(range(2, args.writes - 1), args.crashes))
        cm = c.client.resource("configmaps")
        controller_crashed = False
        for i in range(args.writes):
            cm.create(v1.ConfigMap(
                metadata=v1.ObjectMeta(name=f"acked-{i:03d}", namespace="default")
            ))
            acked.append(f"acked-{i:03d}")  # acked: the create returned
            if crash_at and i == crash_at[0]:
                crash_at.pop(0)
                d = monkey.do_one("crash-apiserver")
                print(f"  write {i}: {d.kind} (rev={c.api.store.revision})")
                if not controller_crashed:
                    d = monkey.do_one("crash-controller")
                    print(f"  write {i}: {d.kind} -> {d.target}")
                    controller_crashed = True
        monkey.restart_all_dead(timeout=30)

        # 1. zero lost acknowledged writes
        names = {o.metadata.name for o in cm.list(namespace="default")[0]}
        lost = sorted(set(acked) - names)
        if lost:
            failures.append(f"lost {len(lost)} acknowledged writes: {lost[:5]}...")
        else:
            print(f"durability: all {len(acked)} acknowledged writes present")

        # 2. informers re-listed and the workload re-converged
        if not wait_until(lambda: n_running() == args.replicas, timeout=60):
            failures.append(
                f"convergence after crash: {n_running()}/{args.replicas} running"
            )
        else:
            print(f"convergence: {args.replicas} replicas running again")
        pods_informer = c.kcm.informers.pods()
        server_pods, _ = c.client.pods.list(namespace="default")
        if not wait_until(
            lambda: pods_informer.count() >= len(server_pods), timeout=15
        ):
            failures.append("informer cache never re-synced to server state")
        else:
            print("informers: caches re-listed and synced")

        # 3. the crashed controller restarted under supervision
        sup = c.kcm.supervisor
        restarted = {n: sup.restart_count(n) for n in sup.names()}
        crashed = [d.target for d in monkey.history if d.kind == "crash-controller"]
        for name in crashed:
            if restarted.get(name, 0) < 1:
                failures.append(f"controller {name} was never restarted")
        if not all(sup.running(n) for n in sup.names()):
            failures.append("not all controllers running after the drill")
        else:
            print(f"supervisor: restarts={restarted}, all loops running")

    if failures:
        print("FAIL:\n  " + "\n  ".join(failures))
        return 1
    print(f"PASS: store dir {path} survived "
          f"{args.crashes} apiserver crashes + a controller crash")
    return 0


if __name__ == "__main__":
    sys.exit(main())
