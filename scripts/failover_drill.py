"""Failover drill: run the scheduler-failover disruption matrix against
a live dual-scheduler cluster and report split-brain safety.

Sibling of fault_drill.py (device faults) and crash_drill.py
(control-plane crashes); this one drills the LEADERSHIP layer: graceful
abdications, leader netsplits (self-fence margin vs a standby's
adoption window), and pipeline-worker kills on the leader — all while a
pod stream keeps both instances' queues warm. Between the scripted
phases it measures failover-to-first-bind latency (leadership lost ->
the promoted standby's first successful bind) and replays a deposed
epoch's bind to prove the apiserver fence rejects it without touching
the store. Prints a recovery report and exits nonzero on any lost,
double-bound, or fence-escaped pod.

Runs on CPU (the TPU backend rides the hoisted session there):

    JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python scripts/failover_drill.py
"""

import argparse
import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.api import types as v1  # noqa: E402
from kubernetes_tpu.apiserver.server import FenceExpired  # noqa: E402
from kubernetes_tpu.cluster import Cluster  # noqa: E402
from kubernetes_tpu.scheduler import metrics  # noqa: E402
from kubernetes_tpu.testing.chaos import ChaosMonkey  # noqa: E402
from kubernetes_tpu.testing.faults import (  # noqa: E402
    BindIntegrityChecker,
    FaultInjector,
)
from kubernetes_tpu.testing.invariants import (  # noqa: E402
    CounterMoved,
    InvariantSuite,
)

# fast lease timings: production defaults (15s/10s/2s) would make every
# failover a coffee break
ELECTION = dict(
    lease_duration=1.5,
    renew_deadline=1.0,
    retry_period=0.05,
    fence_margin=0.3,
)


def wait_until(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def pod(name: str, cpu: str = "20m") -> v1.Pod:
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        spec=v1.PodSpec(containers=[v1.Container(
            name="c", image="img:1",
            resources=v1.ResourceRequirements(requests={"cpu": cpu}),
        )]),
    )


def counter_total(counter) -> float:
    return sum(val for _, val in counter.items())


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--schedulers", type=int, default=2)
    ap.add_argument("--pods", type=int, default=60,
                    help="pod stream length during chaos")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds of chaos")
    ap.add_argument("--period", type=float, default=0.6,
                    help="disruption period")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    rng = random.Random(args.seed)
    inj = FaultInjector()
    failures = []
    transitions0 = metrics.leader_transitions.value()
    rejections0 = counter_total(metrics.fencing_rejections)
    reconcile0 = {k: val for k, val in metrics.restart_reconcile.items()}

    with Cluster(
        n_nodes=args.nodes,
        n_schedulers=args.schedulers,
        election_opts=dict(ELECTION),
        # nodelifecycle lifts the not-ready admission taint; without it
        # every node stays NoSchedule-tainted and nothing ever binds
        controllers=["replicaset", "deployment", "nodelifecycle"],
        controller_opts={
            "node_monitor_period": 0.3,
            "node_monitor_grace_period": 2.0,
        },
        fault_injector=inj,
    ) as c:
        checker = BindIntegrityChecker().attach(c.kcm.informers.pods())
        suite = InvariantSuite([
            # a failover drill whose chaos never flipped the lease, or
            # whose stale replay never hit the fence, proved nothing
            CounterMoved("scheduler_leader_transitions_total", min_delta=2),
            CounterMoved("scheduler_fencing_rejections_total", min_delta=1),
        ])
        if not wait_until(
                lambda: any(s.elector.is_leader.is_set()
                            for s in c.schedulers), timeout=15):
            print("FAIL: no leader elected")
            return 1
        suite.sample()

        for i in range(8):
            c.client.pods.create(pod(f"seed-{i}"))

        def n_bound():
            pods, _ = c.client.pods.list(namespace="default")
            return sum(1 for p in pods if p.spec.node_name)

        if not wait_until(lambda: n_bound() == 8, timeout=30):
            print(f"FAIL: initial convergence ({n_bound()}/8)")
            return 1
        leader = c.active_scheduler
        print(f"seeded: 8 pods on {args.nodes} nodes, leader "
              f"{leader.elector.cfg.identity} "
              f"(epoch {leader.elector.fencing_token().transitions})")

        # -- measured failover: leadership lost -> first bind by the
        # promoted standby (the pods created at t0 can only be bound by
        # the successor; the old leader is demoted and paused)
        old = leader
        t0 = time.monotonic()
        old.elector.abdicate(cooldown=2.0 * ELECTION["lease_duration"])
        for i in range(4):
            c.client.pods.create(pod(f"failover-{i}"))
        if not wait_until(lambda: n_bound() == 12, timeout=30):
            failures.append(
                f"failover batch never bound ({n_bound()}/12)")
            latency = float("nan")
        else:
            latency = time.monotonic() - t0
        new = c.active_scheduler
        print(f"failover: {old.elector.cfg.identity} -> "
              f"{new.elector.cfg.identity}, first bind after "
              f"{latency * 1000:.0f} ms")

        # -- stale-epoch replay: the deposed leader's latched token must
        # bounce off the apiserver fence without touching the store
        stale = old._fence
        live_epoch = new.elector.fencing_token().transitions
        if stale is None or stale.transitions >= live_epoch:
            failures.append(
                f"no stale token to replay (old fence {stale}, live "
                f"epoch {live_epoch})")
        else:
            # a pod no node can fit: the live leader parks it
            # unschedulable, so nothing races the replay
            c.client.pods.create(pod("fence-probe", cpu="999000m"))
            nodes, _ = c.client.nodes.list()
            try:
                c.client.pods.bind("default", "fence-probe",
                                   nodes[0].metadata.name, fence=stale)
                failures.append(
                    f"stale epoch {stale.transitions} bind was ACCEPTED "
                    f"(live epoch {live_epoch}) — the fence is open")
            except FenceExpired as e:
                print(f"fence held: {e}")
            probe = c.client.pods.get("fence-probe", "default")
            if probe.spec.node_name:
                failures.append(
                    f"rejected stale bind still mutated the store: "
                    f"fence-probe bound to {probe.spec.node_name!r}")
            c.client.pods.delete("fence-probe", "default")
        suite.sample()

        # -- chaos: abdications + netsplits + leader pipeline kills over
        # a pod stream
        monkey = ChaosMonkey(
            c, period=args.period, rng=rng,
            disruptions=["failover-scheduler", "partition-scheduler",
                         "crash-scheduler"],
        )
        monkey.run()
        created = 0
        deadline = time.monotonic() + args.duration
        last_sample = 0.0
        while time.monotonic() < deadline:
            for _ in range(rng.randrange(1, 5)):
                if created < args.pods:
                    c.client.pods.create(pod(f"w-{created}"))
                    created += 1
            if time.monotonic() - last_sample >= 0.5:
                last_sample = time.monotonic()
                suite.sample()
            time.sleep(0.05)
        while created < args.pods:
            c.client.pods.create(pod(f"w-{created}"))
            created += 1
        monkey.stop()
        inj.disarm()
        monkey.restart_all_dead(timeout=30)

        total = 12 + args.pods  # seeds + failover batch + stream

        def converged():
            pods, _ = c.client.pods.list(namespace="default")
            return (len(pods) == total
                    and all(p.spec.node_name for p in pods))

        if not wait_until(converged, timeout=90):
            pods, _ = c.client.pods.list(namespace="default")
            unbound = [p.metadata.name for p in pods if not p.spec.node_name]
            failures.append(
                f"lost pods: {len(unbound)} unbound of {len(pods)} "
                f"({total} expected): {unbound[:8]}")
        if checker.violations:
            failures.append(f"double binds: {checker.violations}")
        failures.extend(suite.finish())

        by_kind = {}
        for d in monkey.history:
            by_kind[d.kind] = by_kind.get(d.kind, 0) + 1
        reconcile_delta = {
            k[0]: val - reconcile0.get(k, 0.0)
            for k, val in metrics.restart_reconcile.items()
            if val - reconcile0.get(k, 0.0) > 0
        }
        print("--- recovery report ---")
        print(f"disruptions:         {by_kind}")
        print(f"leader transitions:  "
              f"{metrics.leader_transitions.value() - transitions0:.0f}")
        print(f"fencing rejections:  "
              f"{counter_total(metrics.fencing_rejections) - rejections0:.0f}")
        print(f"reconcile outcomes:  {reconcile_delta}")
        print(f"failover-to-first-bind: {latency * 1000:.0f} ms")
        print(f"final bind count:    {n_bound()}/{total}")

    if failures:
        print("FAIL:\n  " + "\n  ".join(failures))
        return 1
    print("PASS: leadership survived the failover matrix "
          "(zero lost, zero double-bound, fence held)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
