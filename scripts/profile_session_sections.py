"""Section attribution for the hoisted session step: toggle sections off
and measure the scan slope (ms/pod) on the real chip.

Duplicates ops/hoisted.py _step with skip flags — a throwaway probe, not
product code; parity is irrelevant here, only cost structure.
"""
import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import copy
import functools
import numpy as np
import jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops import kernel as K
from kubernetes_tpu.ops import hoisted as H
from kubernetes_tpu.ops.kernel import _CNT, _F64, _I64, DEFAULT_WEIGHTS
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))


def make_step(skip):
    """_step clone; names in `skip` replace that section with a constant."""

    def step(S, c_static, weights, carry, x):
        tj = x["tmpl"]
        j = x["j"]
        n = c_static["valid"].shape[0]
        vnp = c_static["npair"].shape[1]
        col = jnp.arange(vnp)[None, :]
        sel = lambda key: S[key][tj]

        req = sel("req")
        if "fit" in skip:
            mask_fit = jnp.ones(n, bool)
        else:
            mask_fit = K.fit_mask(
                carry["requested"], carry["pod_count"], c_static["alloc"],
                c_static["allowed_pods"], req, sel("req_check"), sel("req_has_any"),
            )

        if "ptsf" in skip:
            mask_pts = jnp.ones(n, bool)
        else:
            f_valid = sel("f_valid")
            any_f = jnp.any(f_valid)
            cnt = carry["f_cnt"][tj]
            shared = jnp.sum(
                jnp.where(sel("f_same_key")[:, :, None], cnt[None, :, :], 0), axis=1
            )
            reg_real = sel("f_reg_real")
            big = jnp.iinfo(_CNT).max
            min_c = jnp.min(jnp.where(reg_real, shared, big), axis=1)
            min_c = jnp.where(min_c == big, 0, min_c)
            pair_cn = sel("f_pair_cn")
            cnt_n = jnp.take_along_axis(shared.T, pair_cn, axis=0)
            reg_n = jnp.take_along_axis(reg_real.T, pair_cn, axis=0)
            cnt_n = jnp.where(reg_n, cnt_n, 0)
            key_on_node = sel("f_key_on_node")
            fail_missing = jnp.any(f_valid[None, :] & ~key_on_node, axis=1)
            skew = cnt_n + sel("f_self_match")[None, :] - min_c[None, :]
            fail_skew = jnp.any(
                f_valid[None, :] & key_on_node & (skew > sel("f_skew")[None, :]),
                axis=1,
            )
            mask_pts = ~(any_f & (fail_missing | fail_skew))

        feasible = sel("static_mask") & mask_fit & mask_pts

        nz_req = sel("nz_req")
        if "res_scores" in skip:
            sc_balanced = jnp.zeros(n, _I64)
            sc_least = jnp.zeros(n, _I64)
        else:
            sc_balanced = K.balanced_score(
                carry["nz_requested"], nz_req, c_static["alloc"])
            sc_least = K.least_allocated_score(
                carry["nz_requested"], nz_req, c_static["alloc"])

        if "ptss" in skip:
            sc_pts = jnp.zeros(n, _I64)
        else:
            s_valid = sel("s_valid")
            any_s = jnp.any(s_valid)
            has_all = sel("s_has_all")
            hostname = sel("s_hostname")
            scored = feasible & has_all
            ignored = feasible & ~has_all
            pair_cn_s = sel("s_pair_cn")
            if "ptss_reg" in skip:
                reg_real_s = sel("f_reg_real") & (col > 0)  # wrong but cheap
            else:
                reg_s = jax.vmap(
                    lambda pids: K._seg_max_bool(
                        scored, jnp.where(scored, pids, 0), vnp),
                    in_axes=1,
                )(pair_cn_s)
                reg_real_s = reg_s & (col > 0) & ~hostname[:, None] & s_valid[:, None]
            topo_size = jnp.where(
                sel("s_first"), jnp.sum(reg_real_s, axis=1), 0).astype(_F64)
            n_scored = jnp.sum(scored).astype(_F64)
            weight = jnp.log(jnp.where(hostname, n_scored, topo_size) + 2.0)
            shared_s = jnp.sum(
                jnp.where(sel("s_same_key")[:, :, None],
                          carry["s_cnt"][tj][None, :, :], 0),
                axis=1,
            )
            cnt_n_s = jnp.take_along_axis(shared_s.T, pair_cn_s, axis=0)
            reg_n_s = jnp.take_along_axis(reg_real_s.T, pair_cn_s, axis=0)
            cnt_n_s = jnp.where(reg_n_s, cnt_n_s, 0)
            cnt_n_s = jnp.where(hostname[None, :], carry["h_cnt"][tj].T, cnt_n_s)
            terms = jnp.where(
                s_valid[None, :] & sel("s_key_on_node"),
                cnt_n_s.astype(_F64) * weight[None, :]
                + (sel("s_skew")[None, :].astype(_F64) - 1.0),
                0.0,
            )
            raw = jnp.sum(terms, axis=1).astype(_I64)
            big64 = jnp.iinfo(jnp.int64).max
            min_r = jnp.min(jnp.where(scored, raw, big64))
            max_r = jnp.max(jnp.where(scored, raw, 0))
            min_r = jnp.where(min_r == big64, 0, min_r)
            norm = K.MAX_NODE_SCORE * (max_r + min_r - raw) // jnp.where(
                max_r == 0, 1, max_r)
            norm = jnp.where(max_r == 0, K.MAX_NODE_SCORE, norm)
            norm = jnp.where(ignored, 0, norm)
            sc_pts = jnp.where(any_s, norm, 0)

        if "norms" in skip:
            sc_ipa = jnp.zeros(n, _I64)
            sc_taint = jnp.zeros(n, _I64)
            sc_nodeaff = jnp.zeros(n, _I64)
        else:
            sc_ipa = K._score_ipa_normalize(
                sel("raw_ipa"), sel("ipa_present"), feasible)
            sc_taint = K._normalize_default(
                sel("cnt_taint"), feasible, reverse=True)
            sc_nodeaff = K._normalize_default(
                sel("cnt_nodeaff"), feasible, reverse=False)

        total = (
            sc_balanced * DEFAULT_WEIGHTS["balanced"]
            + sel("sc_image") * DEFAULT_WEIGHTS["image"]
            + sc_ipa * DEFAULT_WEIGHTS["ipa"]
            + sc_least * DEFAULT_WEIGHTS["least"]
            + sc_nodeaff * DEFAULT_WEIGHTS["node_affinity"]
            + sel("sc_avoid") * DEFAULT_WEIGHTS["prefer_avoid"]
            + sc_pts * DEFAULT_WEIGHTS["pts"]
            + sc_taint * DEFAULT_WEIGHTS["taint"]
        )
        total = jnp.where(feasible, total, -1)
        best = jnp.argmax(total).astype(jnp.int32)
        ok = (total[best] >= 0) & x["valid"]
        add64 = ok.astype(_I64)
        addc = ok.astype(_CNT)
        carry = dict(carry)
        if "carry_util" not in skip:
            carry["requested"] = carry["requested"].at[best].add(req * add64)
            carry["nz_requested"] = carry["nz_requested"].at[best].add(nz_req * add64)
            carry["pod_count"] = carry["pod_count"].at[best].add(ok.astype(jnp.int32))
        if "carry_cnt" not in skip:
            t_n = S["f_pair_cn"].shape[0]
            c_n = S["f_pair_cn"].shape[2]
            t_idx = jnp.arange(t_n)[:, None]
            c_idx = jnp.arange(c_n)[None, :]
            mf = S["Mf"][:, j, :] * addc
            ms = S["Ms"][:, j, :] * addc
            pair_b_f = S["f_pair_cn"][:, best, :]
            pair_b_s = S["s_pair_cn"][:, best, :]
            src_b = S["s_src"][:, best]
            carry["f_cnt"] = carry["f_cnt"].at[t_idx, c_idx, pair_b_f].add(mf)
            carry["s_cnt"] = carry["s_cnt"].at[t_idx, c_idx, pair_b_s].add(
                ms * src_b[:, None].astype(_CNT))
            carry["h_cnt"] = carry["h_cnt"].at[:, :, best].add(ms)
        y = {"best": jnp.where(ok, best, -1),
             "score": jnp.where(ok, total[best], -1),
             "n_feasible": jnp.sum(feasible.astype(jnp.int32))}
        return carry, y

    return step


def main():
    nodes, init_pods = synth_cluster(N, pods_per_node=2)
    pending = synth_pending_pods(600, spread=True)
    phantoms = []
    for i, p in enumerate(pending):
        q = copy.deepcopy(p); q.metadata.name = f"ph-{i}"
        q.spec.node_name = nodes[i % len(nodes)].metadata.name
        phantoms.append(q)
    enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
    pe = PodEncoder(enc)
    for p in pending: pe.encode(p)
    enc.device_state()
    for q in phantoms: enc.remove_pod(q)
    arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")}
              for p in pending]
    c = enc.device_state()
    templates, seen = [], set()
    for a in arrays:
        fp = H.template_fingerprint(a)
        if fp not in seen: seen.add(fp); templates.append(a)
    print("device:", jax.devices()[0], " templates:", len(templates))
    # deliberately trigger the tunnel's sync mode so timings are honest
    # (any D2H flips it; without this, block_until_ready returns before
    # the work actually runs and slopes are enqueue-cost illusions)
    poison = jax.numpy.arange(4) + 1
    jax.block_until_ready(poison)
    np.asarray(poison)

    variants = [
        ("full", frozenset()),
        ("-ptsf", frozenset({"ptsf"})),
        ("-ptss", frozenset({"ptss"})),
        ("-ptss_reg", frozenset({"ptss_reg"})),
        ("-res_scores", frozenset({"res_scores"})),
        ("-norms", frozenset({"norms"})),
        ("-carry_cnt", frozenset({"carry_cnt"})),
        ("-fit", frozenset({"fit"})),
        ("minimal", frozenset({"ptsf", "ptss", "norms", "res_scores", "carry_cnt"})),
    ]
    orig = H._step
    # slope via two batch sizes so fixed dispatch cost cancels
    B1, B2 = 128, 512
    for name, skip in variants:
        H._step = make_step(skip)
        H._session_scan._clear_cache()
        sess = H.HoistedSession(c, templates)
        def run(b):
            ys = sess.schedule(arrays[:b])
            jax.block_until_ready(ys["best"])
        run(B1); run(B2)  # compile both shapes
        t1 = []
        t2 = []
        for _ in range(3):
            t0 = time.perf_counter(); run(B1); t1.append(time.perf_counter() - t0)
            t0 = time.perf_counter(); run(B2); t2.append(time.perf_counter() - t0)
        slope = (min(t2) - min(t1)) / (B2 - B1) * 1e3
        print(f"{name:12s} slope={slope:6.3f} ms/pod  "
              f"B{B1}={min(t1)*1e3:7.1f}ms B{B2}={min(t2)*1e3:7.1f}ms")
    H._step = orig


main()
