#!/usr/bin/env python3
"""ktpu-lint CLI.

Usage:
  python scripts/lint.py                 lint the package (exit 1 on
                                         any non-baselined violation)
  python scripts/lint.py --explain       also list pragma-waived sites
                                         with their reasons, and any
                                         baselined debt
  python scripts/lint.py --json          machine-readable report on
                                         stdout (for automation)
  python scripts/lint.py --update-baseline
                                         re-record analysis/baseline.json
                                         to the current violation set
  python scripts/lint.py --knob-table    print the README KTPU_* knob
                                         table from the live registry
  python scripts/lint.py --no-cache      ignore the per-file mtime cache

The checkers and their pragma rules (# ktpu: allow-<rule>(<reason>)):
  host-sync       sync        decision-inert  inert
  knob-registry   knob        seam-pairing    seam
  lock-order      lock
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description="ktpu-lint")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--explain", action="store_true",
                    help="list pragma-waived sites and baselined debt")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-record analysis/baseline.json")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the README knob table from the registry")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore the per-file mtime cache")
    args = ap.parse_args()

    if args.knob_table:
        # the only mode that imports package runtime code (knobs.py is
        # dependency-free; the checkers themselves never import it)
        from kubernetes_tpu.utils import knobs
        print(knobs.markdown_table())
        return 0

    from kubernetes_tpu.analysis import core

    if args.update_baseline:
        report = core.update_baseline()
        n = len(report.baselined)
        print(f"baseline re-recorded: {n} grandfathered entr"
              f"{'y' if n == 1 else 'ies'}")
        return 0 if report.clean else 1

    report = core.run(use_cache=not args.no_cache)

    if args.json:
        json.dump(report.to_json(), sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0 if report.clean else 1

    for v in report.violations:
        print(f"{v.path}:{v.line}: [{v.checker}/{v.code}] {v.message} "
              f"(in {v.func})")
    if args.explain:
        if report.allowed:
            print(f"\n-- {len(report.allowed)} pragma-waived site(s):")
            for a in sorted(report.allowed,
                            key=lambda a: (a.path, a.line)):
                print(f"  {a.path}:{a.line} [{a.checker}/{a.code}] "
                      f"allowed: {a.reason}")
        if report.baselined:
            print(f"\n-- {len(report.baselined)} baselined (grandfathered) "
                  "violation(s):")
            for v in report.baselined:
                print(f"  {v.path}:{v.line} [{v.checker}/{v.code}] {v.key}")
    if report.stale_baseline:
        print(f"\n-- {len(report.stale_baseline)} stale baseline entr"
              f"{'y' if len(report.stale_baseline) == 1 else 'ies'} "
              "(fixed! shrink with --update-baseline):")
        for k in report.stale_baseline:
            print(f"  {k}")

    cached = report.files_from_cache
    print(f"\nktpu-lint: {len(report.violations)} violation(s), "
          f"{len(report.baselined)} baselined, {len(report.allowed)} "
          f"allowed by pragma ({report.files_checked} files, "
          f"{cached} from cache)")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
