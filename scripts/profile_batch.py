"""Phase-level profiling of the batched scheduling cycle (dev tool)."""

import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import CARRY_KEYS, _scan_batch, schedule_batch
from kubernetes_tpu.ops.kernel import DEFAULT_WEIGHTS
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

import numpy as np
import jax.numpy as jnp

N_NODES = int(os.environ.get("BENCH_NODES", "500"))
B = int(os.environ.get("BENCH_BATCH", "100"))

nodes, init_pods = synth_cluster(N_NODES, pods_per_node=2)
pending = synth_pending_pods(4 * B, spread=True)

enc = ClusterEncoding()
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"phantom-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]:
    pe.encode(p)
enc.device_state()
for q in phantoms:
    enc.remove_pod(q)


def run_batch(pods, label):
    t0 = time.perf_counter()
    arrays = [
        {k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pods
    ]
    t1 = time.perf_counter()
    c = enc.device_state()
    jax.block_until_ready(c)
    t2 = time.perf_counter()
    stacked = {
        k: jnp.asarray(np.stack([np.asarray(pa[k]) for pa in arrays]))
        for k in arrays[0]
    }
    xs = {
        "pod": stacked,
        "pidx": jnp.asarray(
            np.asarray([enc._pod_free[-1 - i] for i in range(len(pods))], np.int32)
        ),
        "valid": jnp.ones(len(pods), bool),
    }
    jax.block_until_ready(xs)
    t3 = time.perf_counter()
    static_c = {k: v for k, v in c.items() if k not in CARRY_KEYS}
    carry = {k: c[k] for k in CARRY_KEYS}
    key = tuple(sorted(DEFAULT_WEIGHTS.items()))
    new_carry, ys = _scan_batch(static_c, carry, xs, key)
    jax.block_until_ready((new_carry, ys))
    t4 = time.perf_counter()
    decisions = [int(v) for v in np.asarray(ys["best"])]
    for pod, best in zip(pods, decisions):
        if best < 0:
            continue
        pod.spec.node_name = enc.node_names[best]
        enc.add_pod(pod, enc.node_names[best])
    t5 = time.perf_counter()
    print(
        f"{label}: encode={t1-t0:.3f}s sync={t2-t1:.3f}s stack={t3-t2:.3f}s "
        f"scan={t4-t3:.3f}s host_add={t5-t4:.3f}s total={t5-t0:.3f}s",
        flush=True,
    )


for i in range(4):
    run_batch(pending[i * B : (i + 1) * B], f"batch{i}")
