"""Render a flight-recorder dump: Chrome-trace JSON + text stage report.

Input: a dump file written by the flight recorder (utils/tracing.py
FlightRecorder.dump — the KTPU_TRACE_DUMP_DIR files every fault seam
emits, or scripts/fault_drill.py --dump-trace's end-of-drill snapshot).

Output:
  - <dump>.chrome.json (or --chrome PATH): Chrome-trace "trace event
    format" — load in chrome://tracing or https://ui.perfetto.dev
  - stdout: per-stage latency summary (count, total, p50/p99) plus the
    provenance mix (rung / session / planner path / speculation) when
    the dump was taken at KTPU_TRACE=2

Exits nonzero on an unreadable/empty dump — the fault drill runs this
renderer as one of its integrity checks, so a fault seam that emitted a
record nothing can render fails the drill, not just the retro.

Usage: python scripts/trace_report.py DUMP.json [--chrome OUT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.utils import tracing  # noqa: E402


def render(dump_path: str, chrome_path: str = "") -> int:
    """Render one dump file; returns a process exit code."""
    try:
        with open(dump_path) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        print(f"FAIL: unreadable dump {dump_path}: {e}", file=sys.stderr)
        return 1
    events = record.get("events") or []
    if not events:
        print(f"FAIL: dump {dump_path} holds no events "
              f"(reason={record.get('reason')!r})", file=sys.stderr)
        return 1

    chrome = tracing.chrome_trace(events)
    out_path = chrome_path or (os.path.splitext(dump_path)[0]
                               + ".chrome.json")
    with open(out_path, "w") as f:
        json.dump({"traceEvents": chrome,
                   "metadata": {"reason": record.get("reason"),
                                "level": record.get("level")}}, f)

    print(f"dump: {dump_path}")
    print(f"reason: {record.get('reason')}  level: {record.get('level')}  "
          f"events: {len(events)}")
    if record.get("attrs"):
        print(f"fault attrs: {record['attrs']}")
    print(f"chrome trace: {out_path}  (load in chrome://tracing)")
    print()
    print(f"{'stage':<16}{'count':>7}{'total_s':>10}{'p50_ms':>9}"
          f"{'p99_ms':>9}")
    for stage, s in tracing.stage_stats(events).items():
        print(f"{stage:<16}{s['count']:>7}{s['total_s']:>10.4f}"
              f"{s['p50_s'] * 1e3:>9.2f}{s['p99_s'] * 1e3:>9.2f}")
    mix = tracing.provenance_mix(events)
    if mix:
        print()
        print("provenance mix (per decided pod):")
        for field, vals in sorted(mix.items()):
            pretty = ", ".join(
                f"{k}={v}" for k, v in
                sorted(vals.items(), key=lambda kv: -kv[1])
            )
            print(f"  {field:<14}{pretty}")
    window = tracing.window_span(events)
    print()
    print(f"window: {window:.3f}s covered by recorded spans")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="flight-recorder dump JSON")
    ap.add_argument("--chrome", default="",
                    help="chrome-trace output path "
                         "(default: <dump>.chrome.json)")
    args = ap.parse_args()
    return render(args.dump, args.chrome)


if __name__ == "__main__":
    sys.exit(main())
