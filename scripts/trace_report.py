"""Render a flight-recorder dump: Chrome-trace JSON + text stage report.

Input: a dump file written by the flight recorder (utils/tracing.py
FlightRecorder.dump — the KTPU_TRACE_DUMP_DIR files every fault seam
emits, or scripts/fault_drill.py --dump-trace's end-of-drill snapshot).

Output:
  - <dump>.chrome.json (or --chrome PATH): Chrome-trace "trace event
    format" — load in chrome://tracing or https://ui.perfetto.dev
  - stdout: per-stage latency summary (count, total, p50/p99) plus the
    provenance mix (rung / session / planner path / speculation) when
    the dump was taken at KTPU_TRACE=2

Exits nonzero on an unreadable/empty dump — the fault drill runs this
renderer as one of its integrity checks, so a fault seam that emitted a
record nothing can render fails the drill, not just the retro.

With --devtime DEVTIME.json (a utils/devtime.py DeviceTimeline.dump
file — the seams write one beside every ring dump) the device timeline
merges into the chrome export as a separate track (pid=1, one tid per
kind: kernel/transfer/compile) aligned to the host spans in the shared
perf_counter timebase, the overlap summary prints, and the exit code
additionally gates on timeline<->span reconciliation: every record's
ready >= submit, device_busy <= window, host_busy <= window, and
overlapped <= min(host_busy, device_busy). A mismatch means the two
recorders disagree about the same wall-clock — a triage artifact nobody
should trust — so the drill fails loudly instead.

Usage: python scripts/trace_report.py DUMP.json [--chrome OUT.json]
                                      [--devtime DEVTIME.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.utils import devtime, tracing  # noqa: E402

# reconciliation slack: both recorders round their dump floats through
# JSON; a few µs of slack keeps the gate about real disagreement
_RECON_EPS = 1e-4


def _reconcile(dt_records, ov) -> int:
    """Timeline<->span reconciliation gate; returns the number of
    violated invariants (0 = clean)."""
    bad = 0
    for d in dt_records:
        if d["ready"] + _RECON_EPS < d["submit"]:
            print(f"FAIL: record seq={d['seq']} {d['kind']}:{d['name']} "
                  f"has ready < submit", file=sys.stderr)
            bad += 1
    window = ov["window_s"] + _RECON_EPS
    for side in ("device_busy_s", "host_busy_s"):
        if ov[side] > window:
            print(f"FAIL: {side}={ov[side]} exceeds window_s="
                  f"{ov['window_s']}", file=sys.stderr)
            bad += 1
    floor = min(ov["device_busy_s"], ov["host_busy_s"])
    if ov["overlapped_s"] > floor + _RECON_EPS:
        print(f"FAIL: overlapped_s={ov['overlapped_s']} exceeds "
              f"min(host, device)={floor}", file=sys.stderr)
        bad += 1
    return bad


def render(dump_path: str, chrome_path: str = "",
           devtime_path: str = "") -> int:
    """Render one dump file; returns a process exit code."""
    try:
        with open(dump_path) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        print(f"FAIL: unreadable dump {dump_path}: {e}", file=sys.stderr)
        return 1
    events = record.get("events") or []
    if not events:
        print(f"FAIL: dump {dump_path} holds no events "
              f"(reason={record.get('reason')!r})", file=sys.stderr)
        return 1

    dt_records = []
    if devtime_path:
        try:
            with open(devtime_path) as f:
                dt_dump = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL: unreadable devtime dump {devtime_path}: {e}",
                  file=sys.stderr)
            return 1
        dt_records = dt_dump.get("records") or []
        if not dt_records:
            print(f"FAIL: devtime dump {devtime_path} holds no records "
                  f"(reason={dt_dump.get('reason')!r})", file=sys.stderr)
            return 1

    chrome = tracing.chrome_trace(events)
    if dt_records:
        chrome = chrome + devtime.device_track(dt_records)
    out_path = chrome_path or (os.path.splitext(dump_path)[0]
                               + ".chrome.json")
    with open(out_path, "w") as f:
        json.dump({"traceEvents": chrome,
                   "metadata": {"reason": record.get("reason"),
                                "level": record.get("level")}}, f)

    print(f"dump: {dump_path}")
    print(f"reason: {record.get('reason')}  level: {record.get('level')}  "
          f"events: {len(events)}")
    if record.get("attrs"):
        print(f"fault attrs: {record['attrs']}")
    print(f"chrome trace: {out_path}  (load in chrome://tracing)")
    print()
    print(f"{'stage':<16}{'count':>7}{'total_s':>10}{'p50_ms':>9}"
          f"{'p99_ms':>9}")
    for stage, s in tracing.stage_stats(events).items():
        print(f"{stage:<16}{s['count']:>7}{s['total_s']:>10.4f}"
              f"{s['p50_s'] * 1e3:>9.2f}{s['p99_s'] * 1e3:>9.2f}")
    mix = tracing.provenance_mix(events)
    if mix:
        print()
        print("provenance mix (per decided pod):")
        for field, vals in sorted(mix.items()):
            pretty = ", ".join(
                f"{k}={v}" for k, v in
                sorted(vals.items(), key=lambda kv: -kv[1])
            )
            print(f"  {field:<14}{pretty}")
    window = tracing.window_span(events)
    print()
    print(f"window: {window:.3f}s covered by recorded spans")

    if dt_records:
        summary = devtime.device_time_summary(dt_records)
        ov = devtime.overlap(dt_records, events)
        print()
        print(f"device timeline: {len(dt_records)} records "
              f"(kernel {summary['kernel_s']:.4f}s, "
              f"transfer {summary['transfer_s']:.4f}s, "
              f"compile {summary['compile_s']:.4f}s; "
              f"H2D {summary['h2d_bytes']} B, "
              f"D2H {summary['d2h_bytes']} B)")
        print(f"overlap: window {ov['window_s']:.3f}s  "
              f"device_busy {ov['device_busy_s']:.4f}s  "
              f"host_busy {ov['host_busy_s']:.4f}s  "
              f"overlapped {ov['overlapped_s']:.4f}s  "
              f"ratio {ov['overlap_ratio']}")
        bad = _reconcile(dt_records, ov)
        if bad:
            print(f"FAIL: {bad} timeline/span reconciliation "
                  f"mismatch(es)", file=sys.stderr)
            return 1
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="flight-recorder dump JSON")
    ap.add_argument("--chrome", default="",
                    help="chrome-trace output path "
                         "(default: <dump>.chrome.json)")
    ap.add_argument("--devtime", default="",
                    help="device-timeline dump JSON to merge as a "
                         "separate track (+ overlap summary + "
                         "reconciliation gate)")
    args = ap.parse_args()
    return render(args.dump, args.chrome, args.devtime)


if __name__ == "__main__":
    sys.exit(main())
