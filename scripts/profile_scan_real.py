import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import schedule_batch
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N = int(os.environ.get("BENCH_NODES", "5000"))
B = int(os.environ.get("BENCH_BATCH", "100"))
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pods = synth_pending_pods(3 * B, spread=True)
for q in pods: pe.encode(q)
c = enc.device_state()
arrays = [{k: v for k, v in pe.encode(q).items() if not k.startswith("_")} for q in pods]
slots = [enc._pod_free[-1 - i] for i in range(B)]
for r in range(3):
    t0 = time.perf_counter()
    decisions, carry = schedule_batch(c, arrays[r*B:(r+1)*B], slots)
    jax.block_until_ready(carry)
    print(f"round{r}: {(time.perf_counter()-t0)*1000/B:.2f}ms/pod", flush=True)
