import os, sys, time
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.hoisted import HoistedSession, template_fingerprint
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N, B = 5000, 1024
nodes, init_pods = synth_cluster(N, pods_per_node=2)
pending = synth_pending_pods(3 * B, spread=True)
phantoms = []
for i, p in enumerate(pending):
    q = synth_pending_pods(1, spread=True)[0]
    q.metadata.name = f"ph-{i}"
    q.metadata.labels = dict(p.metadata.labels or {})
    q.spec.node_name = nodes[i % len(nodes)].metadata.name
    phantoms.append(q)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods + phantoms)
pe = PodEncoder(enc)
for p in pending[:8]: pe.encode(p)
enc.device_state()
for q in phantoms: enc.remove_pod(q)
arrays = [{k: v for k, v in pe.encode(p).items() if not k.startswith("_")} for p in pending]
templates, seen = [], set()
for a in arrays:
    fp = template_fingerprint(a)
    if fp not in seen: seen.add(fp); templates.append(a)
print("templates:", len(templates))
sess = HoistedSession(enc.device_state(), templates)
def run(sl):
    t0 = time.perf_counter()
    ys = sess.schedule(sl)
    jax.block_until_ready(ys["best"])
    return time.perf_counter() - t0
run(arrays[:B])  # warm/compile
for tag, sl in [("slice0 again", arrays[:B]), ("slice0 3rd", arrays[:B]),
                ("slice1", arrays[B:2*B]), ("slice1 again", arrays[B:2*B]),
                ("slice2", arrays[2*B:3*B]), ("slice0 4th", arrays[:B])]:
    print(f"{tag:14s} {run(sl)*1e3:8.1f}ms")
