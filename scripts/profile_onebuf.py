import os, sys, time, functools
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax.numpy as jnp
from kubernetes_tpu.models.encoding import ClusterEncoding
from kubernetes_tpu.models.pod_encoder import PodEncoder
from kubernetes_tpu.ops.batch import CARRY_KEYS, _step
from kubernetes_tpu.ops.kernel import DEFAULT_WEIGHTS
from kubernetes_tpu.testing.synth import synth_cluster, synth_pending_pods

N, B = 5000, 100
nodes, init_pods = synth_cluster(N, pods_per_node=2)
enc = ClusterEncoding(); enc.set_cluster(nodes, init_pods)
pe = PodEncoder(enc)
pods = synth_pending_pods(3*B, spread=True)
for q in pods: pe.encode(q)
c = enc.device_state()
key = tuple(sorted(DEFAULT_WEIGHTS.items()))
static_c = {k: v for k, v in c.items() if k not in CARRY_KEYS}
carry0 = {k: c[k] for k in CARRY_KEYS}

def pack_one(arrays):
    layout = []
    chunks = []
    off = 0
    for k in sorted(arrays[0]):
        arr = np.stack([np.asarray(a[k]) for a in arrays])
        flat = arr.reshape(B, -1).astype(np.int64)
        layout.append((k, off, flat.shape[1], arr.shape[1:], arr.dtype.str))
        off += flat.shape[1]
        chunks.append(flat)
    return np.concatenate(chunks, axis=1), tuple(layout)

@functools.partial(jax.jit, static_argnames=("weights_key", "layout"))
def scan_onebuf(static_c, carry, buf, weights_key, layout):
    pod = {}
    for k, off, w, shape, dt in layout:
        pod[k] = jax.lax.slice_in_dim(buf, off, off+w, axis=1).reshape((B,)+tuple(shape)).astype(jnp.dtype(dt))
    xs = {"pod": pod, "pidx": jnp.arange(B, dtype=jnp.int32), "valid": jnp.ones(B, bool)}
    step = functools.partial(_step, static_c, dict(weights_key))
    return jax.lax.scan(step, carry, xs)

for r in range(3):
    t0 = time.perf_counter()
    buf, layout = pack_one([{k: v for k, v in pe.encode(q).items() if not k.startswith("_")} for q in pods[r*B:(r+1)*B]])
    t1 = time.perf_counter()
    dbuf = jnp.asarray(buf); jax.block_until_ready(dbuf)
    t2 = time.perf_counter()
    nc, ys = scan_onebuf(static_c, carry0, dbuf, key, layout)
    jax.block_until_ready(ys["best"])
    t3 = time.perf_counter()
    best = np.asarray(ys["best"])
    t4 = time.perf_counter()
    print(f"r{r}: pack={t1-t0:.3f} upload={t2-t1:.3f} exec={t3-t2:.3f} readback={t4-t3:.3f} buf={buf.nbytes//1024}KB", flush=True)
